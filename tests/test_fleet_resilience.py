"""Fleet-boundary resilience: circuit breakers, the fleet-wide retry
budget, the router spill queue, router-side network fault injection,
and first-class attached (unmanaged) replicas. All on scriptable stub
replicas — no device, no bundle boot — so the whole module stays in the
fast tier-1 budget; the live-fleet end-to-end matrix is
``bench.py --chaos-fleet`` (run_tier1.sh phase 8)."""

import json
import threading
import time
import urllib.error
import urllib.request

import pytest

from lambdipy_tpu.fleet import (
    EJECTED,
    READY,
    CircuitBreaker,
    FleetError,
    FleetRouter,
    ReplicaPool,
    RetryBudget,
    SpillQueue,
    affinity,
)
from lambdipy_tpu.fleet.breaker import CLOSED, HALF_OPEN, OPEN
from lambdipy_tpu.runtime.faults import FaultPlan
from lambdipy_tpu.sched.admission import Shed

from test_fleet import StubReplica, _get, _post


@pytest.fixture()
def stub_pair():
    s0, s1 = StubReplica("r0"), StubReplica("r1")
    pool = ReplicaPool(probe_interval=0.1, fail_threshold=1,
                      readmit_passes=2, probe_timeout=2.0)
    pool.attach("r0", s0.url)
    pool.attach("r1", s1.url)
    yield s0, s1, pool
    pool.close()
    for s in (s0, s1):
        try:
            s.kill()
        except Exception:
            pass


# -- circuit breaker state machine (pure, fake clock) ------------------------


def test_breaker_transitions_closed_open_half_open_closed():
    t = [100.0]
    b = CircuitBreaker(fail_threshold=3, open_s=1.0, clock=lambda: t[0])
    assert b.state == CLOSED and not b.blocked()
    b.record_failure()
    b.record_failure()
    assert b.state == CLOSED  # under threshold
    b.record_failure()
    assert b.state == OPEN and b.blocked() and b.opens == 1
    assert b.last_cause == "consecutive_failures"
    # the open interval must elapse before a probe is allowed
    t[0] += 0.5
    assert b.blocked()
    t[0] += 0.6
    assert not b.blocked()
    b.begin_attempt()  # the router picked it: half-open probe in flight
    assert b.state == HALF_OPEN and b.half_open_probes == 1
    assert b.blocked()  # a second pick must not double-probe
    b.record_success()
    assert b.state == CLOSED and b.closes == 1 and not b.blocked()
    # a success resets the consecutive count entirely
    b.record_failure()
    b.record_success()
    b.record_failure()
    b.record_failure()
    assert b.state == CLOSED


def test_breaker_half_open_failure_reopens_with_backoff():
    t = [0.0]
    b = CircuitBreaker(fail_threshold=1, open_s=1.0, max_open_s=3.0,
                       clock=lambda: t[0])
    b.record_failure()
    assert b.state == OPEN and b.open_until == pytest.approx(1.0)
    t[0] = 1.5
    b.begin_attempt()
    b.record_failure()  # the probe failed: reopen, interval doubled
    assert b.state == OPEN and b.opens == 2
    assert b.open_until == pytest.approx(1.5 + 2.0)
    assert b.last_cause == "half_open_probe_failed"
    t[0] = 4.0
    b.begin_attempt()
    b.record_failure()  # doubled again but capped at max_open_s
    assert b.open_until == pytest.approx(4.0 + 3.0)
    t[0] = 8.0
    b.begin_attempt()
    b.record_success()  # close resets the backoff ladder
    b.record_failure()
    assert b.open_until == pytest.approx(8.0 + 1.0)


def test_breaker_abandoned_half_open_probe_reclaims_after_grace():
    """Some router paths never resolve their forward (a 504
    busy-not-dead timeout, a streamed client that went away): an
    unresolved half-open probe must not blackhole the replica forever —
    after ``probe_grace_s`` the slot can be re-claimed, and the next
    resolved probe decides."""
    t = [0.0]
    b = CircuitBreaker(fail_threshold=1, open_s=1.0, probe_grace_s=5.0,
                       clock=lambda: t[0])
    b.record_failure()
    t[0] = 1.5
    b.begin_attempt()  # probe 1 claimed... and never resolved
    assert b.state == HALF_OPEN and b.blocked()
    t[0] = 4.0
    assert b.blocked()  # within grace: still one probe in flight
    t[0] = 7.0          # past 1.5 + 5.0: probe 1 is abandoned
    assert not b.blocked()
    b.begin_attempt()
    assert b.half_open_probes == 2
    assert b.blocked()  # probe 2 now owns the slot
    b.record_success()
    assert b.state == CLOSED and not b.blocked()


def test_breaker_latency_outlier_opens():
    t = [0.0]
    b = CircuitBreaker(fail_threshold=5, open_s=1.0, outlier_ms=100.0,
                       outlier_threshold=3, clock=lambda: t[0])
    for _ in range(2):
        b.record_success(latency_ms=500.0)
    assert b.state == CLOSED
    b.record_success(latency_ms=50.0)  # a fast answer resets the streak
    b.record_success(latency_ms=500.0)
    b.record_success(latency_ms=500.0)
    assert b.state == CLOSED
    b.record_success(latency_ms=500.0)
    assert b.state == OPEN and b.last_cause == "latency_outlier"


def test_retry_budget_ratio_floor_and_window():
    t = [0.0]
    rb = RetryBudget(ratio=0.5, min_retries=1, window_s=10.0,
                     clock=lambda: t[0])
    # floor: with zero primaries, exactly min_retries retries pass
    assert rb.allow_retry()
    assert not rb.allow_retry()
    assert rb.denied == 1
    # primaries buy more retries at the ratio
    for _ in range(4):
        rb.record_request()
    assert rb.allow_retry()      # budget = 1 + 0.5*4 = 3 > 1 used
    assert rb.allow_retry()
    assert not rb.allow_retry()  # 3 >= 3
    # the window slides: old entries stop counting against the budget
    t[0] = 11.0
    rb.record_request()
    assert rb.allow_retry()
    rep = rb.report()
    assert rep["window_primaries"] == 1 and rep["window_retries"] == 1
    assert rep["denied"] == 2


def test_retry_budget_disabled_ratio_zero():
    rb = RetryBudget(ratio=0.0, min_retries=0)
    assert all(rb.allow_retry() for _ in range(20))
    assert rb.denied == 0


# -- spill queue (pure) ------------------------------------------------------


def test_spill_queue_grants_in_policy_order_when_ready():
    ready = [False]
    q = SpillQueue(lambda: ready[0], capacity=8, max_wait_s=5.0,
                   poll_s=0.01, max_inflight=1).start()
    order = []

    def park(cls):
        out = q.park(cls=cls)
        assert not isinstance(out, Shed)
        order.append(cls)
        time.sleep(0.05)
        q.done(out)

    try:
        threads = [threading.Thread(target=park, args=("background",)),
                   threading.Thread(target=park, args=("interactive",))]
        threads[0].start()
        time.sleep(0.1)  # background parks first...
        threads[1].start()
        time.sleep(0.1)
        assert q.depth() == 2 and order == []  # nothing ready: all parked
        ready[0] = True
        for th in threads:
            th.join(timeout=5)
        # ...but the priority policy drains interactive first
        assert order == ["interactive", "background"]
        rep = q.report()
        assert rep["parked"] == 2 and rep["granted"] == 2
        assert rep["wait"]["count"] == 2
    finally:
        q.close()


def test_spill_queue_overflow_and_deadline_shed_with_estimate():
    q = SpillQueue(lambda: False, capacity=1, max_wait_s=0.3,
                   poll_s=0.01).start()
    try:
        results = []
        th = threading.Thread(
            target=lambda: results.append(q.park(cls="interactive")))
        th.start()
        time.sleep(0.1)
        # capacity 1 is taken: the second park overflows IMMEDIATELY,
        # priced with the queue's wait estimate
        out = q.park(cls="interactive")
        assert isinstance(out, Shed) and out.reason == "spill_overflow"
        assert out.code == 503 and out.retry_after_s > 0
        th.join(timeout=5)
        # the parked one expired at the deadline (never ready)
        assert isinstance(results[0], Shed)
        assert results[0].reason == "spill_deadline"
        assert results[0].retry_after_s > 0
        rep = q.report()
        assert rep["expired"] == 1 and rep["overflow"] == 1
        assert rep["depth"] == 0  # expired tickets leave the queue
    finally:
        q.close()


def test_spill_queue_respects_caller_wait_bound():
    q = SpillQueue(lambda: False, capacity=4, max_wait_s=30.0,
                   poll_s=0.01).start()
    try:
        t0 = time.monotonic()
        out = q.park(cls="interactive", wait_s=0.2)
        assert isinstance(out, Shed) and out.reason == "spill_deadline"
        assert time.monotonic() - t0 < 2.0
        assert isinstance(q.park(cls="interactive", wait_s=-1.0), Shed)
    finally:
        q.close()


# -- router: spill absorption ------------------------------------------------


def test_router_spill_absorbs_transient_fleet_wide_shed(stub_pair):
    """The tentpole claim: a transient fleet-wide shed burst completes
    with ZERO client-visible 429/503s when queue capacity suffices —
    the router parks the burst and drains it on recovery."""
    s0, s1, pool = stub_pair
    pool.probe_all()
    s0.cfg["shed"] = s1.cfg["shed"] = True
    router = FleetRouter(pool, affinity_on=False, max_retries=1,
                         backoff_s=0.01, backoff_cap_s=0.05,
                         spill_cap=16, spill_max_wait_s=10.0)
    router.start_background()
    base = f"http://127.0.0.1:{router.port}"
    results, errors = [], []

    def one(i):
        try:
            results.append(_post(f"{base}/invoke", {"tokens": [i]}))
        except Exception as e:  # noqa: BLE001 — collected for assert
            errors.append(repr(e))

    try:
        threads = [threading.Thread(target=one, args=(i,))
                   for i in range(4)]
        for t in threads:
            t.start()
        time.sleep(0.4)  # the burst is parked, not shed
        assert not errors and not results
        s0.cfg["shed"] = s1.cfg["shed"] = False  # fleet recovers
        for t in threads:
            t.join(timeout=15)
        assert not errors, f"client-visible errors: {errors[:3]}"
        assert len(results) == 4 and all(r["ok"] for r in results)
        rep = router.stats.report()
        assert rep["spill"]["spilled"] == 4
        assert rep["spill"]["drained"] >= 4
        assert rep["spill"]["expired"] == 0
        assert router.metrics()["router"]["spill"]["wait"]["count"] >= 4
    finally:
        router.stop()


def test_router_spill_deadline_sheds_with_wait_estimate(stub_pair):
    """Satellite: when the spill queue itself sheds, the response
    carries the queue's OWN wait estimate in the same wire format the
    server-side shed uses (integer Retry-After header + exact float
    retry_after_s in the body) — the shape the router's own
    ``_retry_after_s`` parses."""
    s0, s1, pool = stub_pair
    pool.probe_all()
    s0.cfg["shed"] = s1.cfg["shed"] = True  # and they never recover
    router = FleetRouter(pool, affinity_on=False, max_retries=1,
                         backoff_s=0.01, backoff_cap_s=0.05,
                         spill_cap=8, spill_max_wait_s=0.5)
    router.start_background()
    base = f"http://127.0.0.1:{router.port}"
    try:
        with pytest.raises(urllib.error.HTTPError) as e:
            _post(f"{base}/invoke", {"tokens": [1]})
        assert e.value.code == 503
        assert int(e.value.headers["Retry-After"]) >= 1
        body = json.loads(e.value.read())
        assert body["shed"] == "spill_deadline"
        assert body["retry_after_s"] > 0
        # the relayed format round-trips through the router's parser
        assert FleetRouter._retry_after_s(
            503, {}, json.dumps(body).encode()) == body["retry_after_s"]
        assert router.stats.report()["spill"]["expired"] == 1

        # the OpenAI surface sheds in the OpenAI error shape
        with pytest.raises(urllib.error.HTTPError) as e:
            _post(f"{base}/v1/completions", {"prompt": [1]})
        err = json.loads(e.value.read())["error"]
        assert err["type"] == "overloaded_error"
        assert err["retry_after_s"] > 0
    finally:
        router.stop()


def test_router_spill_overflow_sheds_excess(stub_pair):
    """With the whole fleet EJECTED (nothing routable, nothing to grant
    onto), a burst past the queue capacity overflows immediately —
    bounded queue, explicit sheds — while the one parked request drains
    once a replica is revived and readmitted."""
    s0, s1, pool = stub_pair
    pool.start()
    port0 = s0.port
    s0.kill()
    s1.kill()
    pool.probe_all()
    assert all(r.state == EJECTED for r in pool.replicas.values())
    router = FleetRouter(pool, affinity_on=False, max_retries=0,
                         backoff_s=0.01, spill_cap=1,
                         spill_max_wait_s=15.0)
    router.start_background()
    base = f"http://127.0.0.1:{router.port}"
    outcomes = []
    s0b = None

    def one(i):
        try:
            outcomes.append(("ok", _post(f"{base}/invoke", {"tokens": [i]})))
        except urllib.error.HTTPError as e:
            outcomes.append(("shed", json.loads(e.read())))

    try:
        threads = [threading.Thread(target=one, args=(i,))
                   for i in range(3)]
        for t in threads:
            t.start()
        time.sleep(0.5)  # 1 parked; the others must have overflowed
        overflowed = [o for kind, o in outcomes if kind == "shed"]
        assert len(overflowed) == 2
        assert all(o["shed"] == "spill_overflow" and o["retry_after_s"] > 0
                   for o in overflowed)
        s0b = StubReplica("r0", port=port0)  # revive -> readmit -> drain
        for t in threads:
            t.join(timeout=15)
        served = [o for kind, o in outcomes if kind == "ok"]
        assert len(served) == 1 and served[0]["ok"]
        rep = router.stats.report()["spill"]
        assert rep["overflow"] == 2 and rep["spilled"] == 3
        assert rep["drained"] >= 1
    finally:
        router.stop()
        if s0b is not None:
            s0b.kill()


def test_router_streams_never_spill(stub_pair):
    """A parked stream would hold a socket open with nothing honest to
    send: streamed requests relay the fleet-wide shed immediately."""
    s0, s1, pool = stub_pair
    pool.probe_all()
    s0.cfg["shed"] = s1.cfg["shed"] = True
    router = FleetRouter(pool, affinity_on=False, max_retries=1,
                         backoff_s=0.01, backoff_cap_s=0.05,
                         spill_cap=8, spill_max_wait_s=30.0)
    router.start_background()
    base = f"http://127.0.0.1:{router.port}"
    try:
        t0 = time.monotonic()
        with pytest.raises(urllib.error.HTTPError) as e:
            _post(f"{base}/invoke", {"tokens": [1], "stream": True})
        assert e.value.code == 503
        assert time.monotonic() - t0 < 5.0  # did not park for 30 s
        assert router.stats.report()["spill"]["spilled"] == 0
    finally:
        router.stop()


# -- router: retry budget ----------------------------------------------------


def test_retry_budget_exhaustion_under_fleet_wide_503(stub_pair):
    """Satellite: under a fleet-wide 503 storm, the budget stops the
    router from re-sending — each shed relays after ONE forward instead
    of max_retries+1, and the denial is counted."""
    s0, s1, pool = stub_pair
    pool.probe_all()
    s0.cfg["shed"] = s1.cfg["shed"] = True
    router = FleetRouter(pool, affinity_on=False, max_retries=3,
                         backoff_s=0.01, backoff_cap_s=0.05,
                         retry_budget=0.01, retry_budget_min=0)
    router.start_background()
    base = f"http://127.0.0.1:{router.port}"
    try:
        for i in range(3):
            with pytest.raises(urllib.error.HTTPError) as e:
                _post(f"{base}/invoke", {"tokens": [i]})
            assert e.value.code == 503  # the honest relayed shed
        rep = router.stats.report()
        assert rep["retry_budget_denied"] >= 3
        # the tiny ratio admits exactly one retry in the window; every
        # further re-send is refused — the fleet saw 4 forwards where
        # an unbudgeted max_retries=3 loop would have sent 12
        assert rep["retries"] == 1
        assert len(s0.bodies) + len(s1.bodies) == 4
        assert router.metrics()["router"]["retry_budget"]["denied"] >= 3
    finally:
        router.stop()


# -- router: circuit breakers ------------------------------------------------


def test_breaker_opens_on_dead_replica_and_half_open_readmits(stub_pair):
    s0, s1, pool = stub_pair
    pool.probe_all()
    # fail_threshold high: the POOL never ejects, isolating the breaker
    pool.fail_threshold = 100
    router = FleetRouter(pool, affinity_on=False, max_retries=2,
                         backoff_s=0.01, backoff_cap_s=0.05,
                         breaker_fails=2, breaker_open_s=0.4)
    router.start_background()
    base = f"http://127.0.0.1:{router.port}"
    try:
        port = s0.port
        s0.kill()
        # every request succeeds via failover; after 2 connect failures
        # the breaker opens and r0 stops being offered at all
        for i in range(6):
            assert _post(f"{base}/invoke", {"tokens": [i]})["ok"]
        b = router.breakers["r0"]
        assert b.state == OPEN and b.opens >= 1
        failovers_at_open = router.stats.report()["failovers"]
        for i in range(4):
            assert _post(f"{base}/invoke",
                         {"tokens": [i]})["replica"] == "r1"
        # open breaker = no further connection attempts at the corpse
        assert router.stats.report()["failovers"] == failovers_at_open

        # revive on the same port: after open_s the next pick half-open
        # probes it, success closes, and traffic returns
        s0b = StubReplica("r0", port=port)
        time.sleep(0.5)
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline and s0b.invokes == 0:
            _post(f"{base}/invoke", {"tokens": [9]})
            time.sleep(0.02)
        assert s0b.invokes >= 1, "traffic never returned to the revived " \
                                 "replica"
        assert b.state == CLOSED and b.closes >= 1
        assert b.half_open_probes >= 1
        rep = router.metrics()["router"]["breakers"]["r0"]
        assert rep["state"] == CLOSED
        s0b.kill()
    finally:
        router.stop()


# -- router-side network fault injection -------------------------------------


def test_fault_grammar_accepts_router_sites():
    plan = FaultPlan.from_spec(
        "route_connect:exception;route_body:exception@seg=2;"
        "route_latency:delay@ms=50;probe:exception@seg=3,n=6")
    assert len(plan.rules) == 4
    with pytest.raises(ValueError):
        FaultPlan.from_spec("route_nowhere:exception")


def test_injected_route_connect_drops_and_fails_over(stub_pair):
    """One injected drop: the request fails over to the other replica
    and still lands. (Two consecutive drops would exhaust a 2-replica
    fleet within one request — that shape is the spill tests' job.)"""
    s0, s1, pool = stub_pair
    pool.probe_all()
    plan = FaultPlan.from_spec("route_connect:exception@seg=1,n=1")
    router = FleetRouter(pool, affinity_on=False, max_retries=3,
                         backoff_s=0.01, backoff_cap_s=0.05, faults=plan)
    router.start_background()
    base = f"http://127.0.0.1:{router.port}"
    try:
        for i in range(4):
            assert _post(f"{base}/invoke", {"tokens": [i]})["ok"]
        rep = router.stats.report()
        assert rep["failovers"] >= 1 and rep["completed"] == 4
        assert plan.counts()["route_connect"] >= 4
    finally:
        router.stop()


def test_injected_route_latency_delays_but_delivers(stub_pair):
    s0, s1, pool = stub_pair
    pool.probe_all()
    plan = FaultPlan.from_spec("route_latency:delay@ms=200,n=1")
    router = FleetRouter(pool, affinity_on=False, faults=plan)
    router.start_background()
    base = f"http://127.0.0.1:{router.port}"
    try:
        t0 = time.monotonic()
        assert _post(f"{base}/invoke", {"tokens": [1]})["ok"]
        assert time.monotonic() - t0 >= 0.2
        assert router.stats.report()["failovers"] == 0
    finally:
        router.stop()


def test_injected_probe_fault_flaps_replica_through_pool(stub_pair):
    s0, s1, pool = stub_pair
    pool.probe_all()  # healthy baseline (counts on the EMPTY plan)
    # a fresh plan counts from zero: its calls 1-2 are the next sweep
    pool.faults = FaultPlan.from_spec("probe:exception@seg=1,n=2")
    pool.probe_all()  # plan calls 1-2: both probes fail -> both ejected
    assert {r.state for r in pool.replicas.values()} == {EJECTED}
    pool.probe_all()
    pool.probe_all()  # two clean passes -> readmitted
    assert all(r.state == READY for r in pool.replicas.values())
    assert all(r.ejections == 1 for r in pool.replicas.values())


# -- first-class attached replicas -------------------------------------------


def test_begin_drain_refuses_attached_replica(stub_pair):
    s0, s1, pool = stub_pair
    pool.probe_all()
    with pytest.raises(FleetError, match="attached.*probe-only"):
        pool.begin_drain("r0")
    assert pool.replicas["r0"].state == READY  # untouched


def test_rolling_restart_refuses_attach_only_pool(stub_pair):
    s0, s1, pool = stub_pair
    pool.probe_all()
    with pytest.raises(FleetError, match="attached"):
        pool.rolling_restart(live_floor=1)
    # not an AttributeError on the missing runtime, and nothing drained
    assert all(r.state == READY for r in pool.replicas.values())


def test_attached_replica_eject_readmit_zero_lost(stub_pair):
    """Attached replicas are first-class for health: kill one mid-
    traffic and every request still lands (failover), the corpse ejects
    at traffic speed, and the revived process readmits on consecutive
    probe passes — zero lost requests end to end."""
    s0, s1, pool = stub_pair
    pool.start()
    pool.probe_all()
    router = FleetRouter(pool, affinity_on=False, max_retries=3,
                         backoff_s=0.01, backoff_cap_s=0.1,
                         spill_cap=16, spill_max_wait_s=10.0)
    router.start_background()
    base = f"http://127.0.0.1:{router.port}"
    stop = threading.Event()
    ok = [0]
    failures = []

    def traffic():
        i = 0
        while not stop.is_set():
            i += 1
            try:
                assert _post(f"{base}/invoke", {"tokens": [i % 7]})["ok"]
                ok[0] += 1
            except Exception as e:  # noqa: BLE001 — collected for assert
                failures.append(repr(e))
            time.sleep(0.02)

    threads = [threading.Thread(target=traffic) for _ in range(2)]
    try:
        for t in threads:
            t.start()
        time.sleep(0.3)
        port = s0.port
        s0.kill()
        victim = pool.replicas["r0"]
        deadline = time.monotonic() + 10
        while victim.state != EJECTED and time.monotonic() < deadline:
            time.sleep(0.05)
        assert victim.state == EJECTED
        time.sleep(0.3)  # traffic rides the survivor
        s0b = StubReplica("r0", port=port)
        deadline = time.monotonic() + 10
        while victim.state != READY and time.monotonic() < deadline:
            time.sleep(0.05)
        assert victim.state == READY and victim.ejections == 1
        time.sleep(0.3)  # traffic over the healed fleet
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=10)
        router.stop()
        try:
            s0b.kill()
        except Exception:
            pass
    assert not failures, f"lost requests: {failures[:3]}"
    assert ok[0] > 10


# -- affinity-aware cache warming --------------------------------------------


def test_warm_prompt_extracts_whole_block_head():
    assert affinity.warm_prompt({"tokens": list(range(70))}, block=32) \
        == list(range(64))
    assert affinity.warm_prompt({"tokens": [1, 2, 3]}, block=32) is None
    assert affinity.warm_prompt({"prompt": "x" * 300}, block=32) \
        == "x" * 256
    # explicit prefix is part of the replayable head
    assert affinity.warm_prompt(
        {"prefix": list(range(32)), "tokens": [1] * 32}, block=32) \
        == list(range(32)) + [1] * 32
    assert affinity.warm_prompt({"n": 3}) is None


def test_readmitted_replica_gets_warmed_with_its_hot_prefixes(stub_pair):
    s0, s1, pool = stub_pair
    pool.start()
    pool.probe_all()
    router = FleetRouter(pool, affinity_on=True, block=4, max_retries=3,
                         backoff_s=0.01, backoff_cap_s=0.1,
                         warm_prefixes=4)
    router.start_background()
    base = f"http://127.0.0.1:{router.port}"
    stubs = {"r0": s0, "r1": s1}
    try:
        # one hot prefix, hammered: the router tracks it
        head = list(range(100, 112))  # 3 whole 4-token blocks
        for i in range(5):
            _post(f"{base}/invoke", {"tokens": head + [i]})
        key = affinity.prefix_key({"tokens": head + [0]}, block=4)
        target = affinity.pick_replica(key, sorted(pool.replicas))
        victim = pool.replicas[target]
        port = stubs[target].port
        stubs[target].kill()
        deadline = time.monotonic() + 10
        while victim.state != EJECTED and time.monotonic() < deadline:
            time.sleep(0.05)
        assert victim.state == EJECTED
        revived = StubReplica(target, port=port)
        deadline = time.monotonic() + 10
        while victim.state != READY and time.monotonic() < deadline:
            time.sleep(0.05)
        assert victim.state == READY
        # the warm request lands on the revived replica: its hot-prefix
        # head as a background-class 1-token completion
        deadline = time.monotonic() + 10
        warm = None
        while warm is None and time.monotonic() < deadline:
            warm = next((b for p, b in revived.bodies
                         if p == "/v1/completions"
                         and b.get("max_tokens") == 1), None)
            time.sleep(0.05)
        assert warm is not None, "readmitted replica never got a warm " \
                                 "request"
        assert warm["prompt"] == head and warm["temperature"] == 0
        assert router.stats.report()["warmed_prefixes"] >= 1
        revived.kill()
    finally:
        router.stop()


def test_router_healthz_reports_spill_depth(stub_pair):
    s0, s1, pool = stub_pair
    pool.probe_all()
    router = FleetRouter(pool, affinity_on=False, spill_cap=4)
    router.start_background()
    try:
        h = _get(f"http://127.0.0.1:{router.port}/healthz")
        assert h["ok"] and h["spill_depth"] == 0
    finally:
        router.stop()


# -- disaggregated prefill/decode: classes, ships, chaos ---------------------


from lambdipy_tpu.fleet import (  # noqa: E402 — section-local imports
    DECODE,
    MIXED,
    PREFILL,
    parse_attach_spec,
)


def test_parse_attach_spec_grammar():
    assert parse_attach_spec("a=http://h:8080") == \
        ("a", "http://h:8080", MIXED)
    assert parse_attach_spec("p0=http://h:8080:prefill") == \
        ("p0", "http://h:8080", PREFILL)
    assert parse_attach_spec("d0=https://h:decode") == \
        ("d0", "https://h", DECODE)
    assert parse_attach_spec("m=http://h:9090:mixed") == \
        ("m", "http://h:9090", MIXED)
    with pytest.raises(FleetError, match="unknown replica class"):
        parse_attach_spec("x=http://h:8080:prefil")
    with pytest.raises(FleetError, match="NAME=URL"):
        parse_attach_spec("http://h:8080")
    with pytest.raises(FleetError, match="NAME=URL"):
        parse_attach_spec("x=ftp://h")


@pytest.fixture()
def disagg_pair():
    """One decode-class + one prefill-class stub behind a router."""
    dec, pre = StubReplica("dec"), StubReplica("pre")
    pool = ReplicaPool(probe_interval=5.0, fail_threshold=1,
                       readmit_passes=2, probe_timeout=2.0)
    pool.attach("dec", dec.url, role=DECODE)
    pool.attach("pre", pre.url, role=PREFILL)
    pool.probe_all()
    yield dec, pre, pool
    pool.close()
    for s in (dec, pre):
        try:
            s.kill()
        except Exception:
            pass


def _router(pool, **kw):
    kw.setdefault("affinity_on", True)
    kw.setdefault("block", 4)
    return FleetRouter(pool, **kw).start_background()


def test_phase_split_ships_then_forwards(disagg_pair):
    """A cold token request exports on the prefill replica, imports on
    the decode replica, and the request itself only ever touches the
    decode replica; a repeat request skips the ship (dedup LRU)."""
    dec, pre, pool = disagg_pair
    router = _router(pool)
    try:
        base = f"http://127.0.0.1:{router.port}"
        row = list(range(1, 13))
        out = _post(f"{base}/invoke", {"tokens": row, "max_new_tokens": 2})
        assert out["ok"] and out["replica"] == "dec"
        assert pre.exports == 1 and len(dec.imports) == 1
        assert dec.imports[0] == pre.cfg["kv_frame"]
        assert pre.invokes == 0  # prefill class never serves decode
        _post(f"{base}/invoke", {"tokens": row, "max_new_tokens": 2})
        assert pre.exports == 1  # second ship deduped
        rep = router.disagg.report()
        assert rep["ships"] == 1 and rep["ship_skips"] == 1
        assert rep["prefill_dispatches"] == 1
        assert rep["decode_dispatches"] == 1
        assert rep["ship_bytes_total"] == len(pre.cfg["kv_frame"])
        assert rep["ship_ms_ewma"] > 0
        assert rep["import_blocks"]["inserted"] == 2
        m = _get(f"{base}/metrics")
        assert m["fleet"]["disagg"]["classes"] == \
            {"decode": 1, "prefill": 1}
        h = _get(f"{base}/healthz")
        assert h["classes"] == {"decode": 1, "prefill": 1}
    finally:
        router.stop()


def test_string_prompt_falls_back_to_mixed(disagg_pair):
    """The router never tokenizes: a string prompt cannot key a KV
    frame, so it serves mixed-mode with the fallback counted."""
    dec, pre, pool = disagg_pair
    router = _router(pool)
    try:
        out = _post(f"http://127.0.0.1:{router.port}/v1/completions",
                    {"prompt": "a" * 64, "max_tokens": 2})
        assert out["ok"] is True  # delivered (stub echoes /invoke shape)
        assert pre.exports == 0
        assert router.disagg.report()["fallbacks"].get("no_token_head") \
            == 1
    finally:
        router.stop()


def test_ship_drop_falls_back_bitwise_and_counted(disagg_pair):
    """Injected kv_ship failure: the request still delivers (identical
    payload — the stub echoes the tokens), the fallback is counted, and
    the prefill replica is NOT ejected (the fault fired router-side,
    before any connection)."""
    dec, pre, pool = disagg_pair
    plan = FaultPlan.from_spec("kv_ship:exception@seg=1,n=2")
    router = _router(pool, faults=plan)
    try:
        base = f"http://127.0.0.1:{router.port}"
        rows = [list(range(10 * i, 10 * i + 8)) for i in range(1, 4)]
        outs = [_post(f"{base}/invoke", {"tokens": r}) for r in rows]
        assert all(o["ok"] and o["replica"] == "dec" for o in outs)
        # delivery is bitwise what a shipless forward returns
        assert [o["echo"] for o in outs] == rows
        rep = router.disagg.report()
        assert rep["fallbacks"]["ship_fault"] == 2
        assert rep["ships"] == 1  # the third request shipped fine
        assert pool.replicas["pre"].state == READY
        assert router.stats.report()["errors"] == 0
    finally:
        router.stop()


def test_ship_latency_delivers_and_prices(disagg_pair):
    """An injected kv_ship delay slows the ship, not the contract: the
    ship lands, the latency EWMA reflects it."""
    dec, pre, pool = disagg_pair
    plan = FaultPlan.from_spec("kv_ship:delay@ms=150,n=1")
    router = _router(pool, faults=plan)
    try:
        base = f"http://127.0.0.1:{router.port}"
        out = _post(f"{base}/invoke", {"tokens": list(range(1, 9))})
        assert out["ok"]
        rep = router.disagg.report()
        assert rep["ships"] == 1 and rep["fallbacks"] == {}
        assert rep["ship_ms_ewma"] >= 150
    finally:
        router.stop()


def test_import_backpressure_falls_back(disagg_pair):
    """A decode replica shedding its import (full page arena) costs the
    ship, never the request — and the shipped-key LRU does NOT mark the
    prefix warm, so the next request re-attempts the ship."""
    dec, pre, pool = disagg_pair
    dec.cfg["kv_shed"] = True
    router = _router(pool)
    try:
        base = f"http://127.0.0.1:{router.port}"
        row = list(range(1, 9))
        out = _post(f"{base}/invoke", {"tokens": row})
        assert out["ok"] and out["replica"] == "dec"
        rep = router.disagg.report()
        assert rep["fallbacks"]["import_backpressure"] == 1
        assert rep["prefill_dispatches"] == 1  # export leg did land
        assert rep["decode_dispatches"] == 0
        dec.cfg["kv_shed"] = False
        out = _post(f"{base}/invoke", {"tokens": row})
        assert router.disagg.report()["decode_dispatches"] == 1
    finally:
        router.stop()


def test_dead_prefill_class_degrades_to_mixed(disagg_pair):
    """Every prefill replica ejected: requests serve mixed-mode on the
    decode class, counted by reason — never an error."""
    dec, pre, pool = disagg_pair
    pre.kill()
    pool.note_failure(pool.replicas["pre"])
    assert pool.replicas["pre"].state == EJECTED
    router = _router(pool)
    try:
        out = _post(f"http://127.0.0.1:{router.port}/invoke",
                    {"tokens": list(range(1, 9))})
        assert out["ok"] and out["replica"] == "dec"
        rep = router.disagg.report()
        assert rep["fallbacks"]["no_prefill_replica"] == 1
        assert rep["ships"] == 0
    finally:
        router.stop()


def test_no_decode_class_degrades_to_prefill_mixed():
    """The inverse hole: only prefill-class replicas routable. The
    router must still deliver (a prefill replica is a full bundle
    server) rather than brown out — counted, never silent."""
    pre = StubReplica("pre")
    pool = ReplicaPool(probe_interval=5.0, probe_timeout=2.0)
    pool.attach("pre", pre.url, role=PREFILL)
    pool.probe_all()
    router = _router(pool)
    try:
        out = _post(f"http://127.0.0.1:{router.port}/invoke",
                    {"tokens": list(range(1, 9))})
        assert out["ok"] and out["replica"] == "pre"
        assert router.disagg.report()["fallbacks"][
            "no_decode_replica"] >= 1
    finally:
        router.stop()
        pool.close()
        pre.kill()


def test_readmission_clears_shipped_keys(disagg_pair):
    """An ejected decode replica's radix cache died with its worker: on
    readmission the router must forget what it shipped there and ship
    again."""
    dec, pre, pool = disagg_pair
    router = _router(pool)
    try:
        base = f"http://127.0.0.1:{router.port}"
        row = list(range(1, 13))
        _post(f"{base}/invoke", {"tokens": row})
        assert pre.exports == 1
        # eject then readmit the decode replica
        r = pool.replicas["dec"]
        pool.note_failure(r)
        assert r.state == EJECTED
        for _ in range(2):
            pool.probe_one(r)
        assert r.state == READY
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline and \
                "dec" in router._shipped:
            time.sleep(0.02)
        _post(f"{base}/invoke", {"tokens": row})
        assert pre.exports == 2  # re-shipped after the cache died
    finally:
        router.stop()


def test_stream_ships_before_first_byte(disagg_pair):
    """Streams ride the phase split too: the ship happens before the
    stream opens, so the decode replica serves the whole stream from
    shipped KV."""
    dec, pre, pool = disagg_pair
    router = _router(pool)
    try:
        base = f"http://127.0.0.1:{router.port}"
        req = urllib.request.Request(
            f"{base}/invoke",
            data=json.dumps({"tokens": list(range(1, 13)),
                             "stream": True}).encode(),
            headers={"Content-Type": "application/json"}, method="POST")
        with urllib.request.urlopen(req, timeout=30) as resp:
            lines = [json.loads(ln) for ln in resp if ln.strip()]
        assert lines and lines[-1].get("done")
        assert pre.exports == 1 and len(dec.imports) == 1
        assert router.disagg.report()["decode_dispatches"] == 1
    finally:
        router.stop()


def test_parse_attach_spec_keeps_odd_urls():
    """The pre-class grammar accepted any http URL: a portless IPv6
    literal or a path-bearing URL must still attach (mixed), only an
    alphabetic non-class suffix raises."""
    assert parse_attach_spec("a=http://[::1]") == \
        ("a", "http://[::1]", MIXED)
    assert parse_attach_spec("a=http://h:8080/base") == \
        ("a", "http://h:8080/base", MIXED)


def _stub_stream_frames(n_blocks=3, block=4):
    """A valid LKVS/LKVC stream (tiny fake KV) a stub export serves."""
    import numpy as np

    from lambdipy_tpu.runtime import kvwire

    rng = np.random.default_rng(0)
    blocks = [[{"k": rng.random((1, block, 2, 4)).astype(np.float32),
                "v": rng.random((1, block, 2, 4)).astype(np.float32)}
               for _ in range(2)] for _ in range(n_blocks)]
    return kvwire.encode_stream(list(range(n_blocks * block)), block,
                                blocks, group=1)


def test_kv_ship_chunk_fault_degrades_and_never_poisons_dedup(
        disagg_pair):
    """An injected mid-stream chunk failure: the request still delivers
    (mixed-mode fallback, counted by reason), NOTHING half-arrived is
    recorded on the decode side, and the ship-dedup LRU is not marked —
    the next request on the same prefix re-ships, and with the fault
    exhausted that ship lands bitwise."""
    dec, pre, pool = disagg_pair
    frames = _stub_stream_frames()
    pre.cfg["kv_stream_frames"] = frames
    plan = FaultPlan.from_spec("kv_ship_chunk:exception@seg=2,n=1")
    router = _router(pool, faults=plan)
    try:
        base = f"http://127.0.0.1:{router.port}"
        row = list(range(1, 13))
        out = _post(f"{base}/invoke", {"tokens": row,
                                       "max_new_tokens": 2})
        assert out["ok"] and out["replica"] == "dec"  # delivered
        rep = router.disagg.report()
        assert rep["fallbacks"].get("ship_chunk_fault") == 1
        assert rep["mid_stream_failures"] >= 1
        assert rep["ships"] == 0
        assert dec.imports == []  # the aborted stream recorded nothing
        assert pre.exports == 1
        # same prefix again: the dedup LRU must NOT claim it shipped —
        # the relay re-ships, and (fault spent) delivers every frame
        out = _post(f"{base}/invoke", {"tokens": row,
                                       "max_new_tokens": 2})
        assert out["ok"]
        assert pre.exports == 2
        assert dec.imports == [b"".join(frames)]  # bitwise delivery
        rep = router.disagg.report()
        assert rep["ships"] == 1 and rep["ships_pipelined"] == 1
        assert rep["chunks_relayed"] == len(frames) - 1
        # and NOW the dedup holds: a third request skips the ship
        _post(f"{base}/invoke", {"tokens": row, "max_new_tokens": 2})
        assert pre.exports == 2
        assert router.disagg.report()["ship_skips"] == 1
    finally:
        router.stop()


def test_kv_ship_chunk_delay_prices_the_relay(disagg_pair):
    """Per-chunk synthetic RTT (the delay kind) slows but never breaks
    the ship: delivered bitwise, EWMA prices the wire time."""
    dec, pre, pool = disagg_pair
    frames = _stub_stream_frames()
    pre.cfg["kv_stream_frames"] = frames
    plan = FaultPlan.from_spec("kv_ship_chunk:delay@ms=40,n=inf")
    router = _router(pool, faults=plan)
    try:
        base = f"http://127.0.0.1:{router.port}"
        t0 = time.monotonic()
        out = _post(f"{base}/invoke", {"tokens": list(range(1, 13)),
                                       "max_new_tokens": 2})
        assert out["ok"]
        assert dec.imports == [b"".join(frames)]
        rep = router.disagg.report()
        assert rep["ships"] == 1 and rep["chunks_relayed"] == 3
        assert rep["mid_stream_failures"] == 0
        assert rep["ship_ms_ewma"] >= 3 * 40
        assert time.monotonic() - t0 >= 0.12
    finally:
        router.stop()


def test_monolithic_ship_window_zero_uses_single_frame(disagg_pair):
    """ship_window=0 is the pre-chunking behavior: one LKV1 frame, no
    chunk relay, the kv_ship_chunk site never fires."""
    dec, pre, pool = disagg_pair
    plan = FaultPlan.from_spec("kv_ship_chunk:exception@seg=1,n=inf")
    router = _router(pool, ship_window=0, faults=plan)
    try:
        out = _post(f"http://127.0.0.1:{router.port}/invoke",
                    {"tokens": list(range(1, 13)), "max_new_tokens": 2})
        assert out["ok"]
        assert dec.imports == [pre.cfg["kv_frame"]]
        rep = router.disagg.report()
        assert rep["ships"] == 1 and rep["ships_pipelined"] == 0
        assert rep["chunks_relayed"] == 0
        assert plan.counts().get("kv_ship_chunk") is None
    finally:
        router.stop()


def test_ship_skips_breaker_blocked_decode_target(disagg_pair):
    """An open decode-replica breaker shields it from ships too — the
    ship must target the replica the forward will actually pick."""
    dec, pre, pool = disagg_pair
    router = _router(pool, breaker_fails=1, breaker_open_s=30.0)
    try:
        # trip dec's breaker (a forward connection failure)
        b = router._breaker(pool.replicas["dec"])
        b.record_failure()
        assert router._breaker_blocked(pool.replicas["dec"])
        out = _post(f"http://127.0.0.1:{router.port}/invoke",
                    {"tokens": list(range(1, 13))})
        # the only decode-capable replica is breaker-blocked: no ship
        # (and the request degraded per the normal pick rules)
        assert pre.exports == 0 and len(dec.imports) == 0
        assert router.disagg.report()["fallbacks"][
            "no_decode_replica"] >= 1
    finally:
        router.stop()
