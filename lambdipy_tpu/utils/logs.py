"""Structured JSON logging.

The reference logs via plain ``click.echo`` to stdout (SURVEY.md §6
metrics/logging row). The rebuild emits one JSON object per line so the
serve runtime's logs are machine-parseable (invoke latencies, cold-start
stages, build provenance).
"""

from __future__ import annotations

import json
import logging
import os
import sys
import time


class _JsonFormatter(logging.Formatter):
    def format(self, record: logging.LogRecord) -> str:
        payload = {
            "ts": round(time.time(), 3),
            "level": record.levelname.lower(),
            "logger": record.name,
            "msg": record.getMessage(),
        }
        extra = getattr(record, "data", None)
        if isinstance(extra, dict):
            payload.update(extra)
        return json.dumps(payload, default=str)


class _LiveStderrHandler(logging.StreamHandler):
    """StreamHandler that resolves ``sys.stderr`` at EMIT time. A handler
    that binds the stream once breaks under test runners (click's
    CliRunner) that swap and then CLOSE sys.stderr per invocation: every
    later log line becomes a '--- Logging error ---' traceback spewed
    into whatever stream is current — polluting captured CLI output."""

    def __init__(self):
        logging.Handler.__init__(self)

    @property
    def stream(self):
        return sys.stderr

    @stream.setter
    def stream(self, value):  # StreamHandler.setStream/init compat
        pass


def get_logger(name: str) -> logging.Logger:
    logger = logging.getLogger(name)
    if not logger.handlers:
        handler = _LiveStderrHandler()
        if os.environ.get("LAMBDIPY_LOG_FORMAT", "json") == "json":
            handler.setFormatter(_JsonFormatter())
        else:
            handler.setFormatter(logging.Formatter("%(levelname)s %(name)s: %(message)s"))
        logger.addHandler(handler)
        logger.setLevel(os.environ.get("LAMBDIPY_LOG_LEVEL", "INFO").upper())
        logger.propagate = False
    return logger


def log_event(logger: logging.Logger, msg: str, **data) -> None:
    logger.info(msg, extra={"data": data})
