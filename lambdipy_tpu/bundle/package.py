"""Bundle assembly: build result + payload -> deployable bundle dir.

The analogue of the reference's ``lambdipy package`` step (SURVEY.md §4 B:
assemble build/ tree + pip-install plain deps), extended with the TPU
payload materialization of SURVEY.md §9.5: model params saved as an orbax
checkpoint inside the bundle, a generated ``handler.py``, and (optionally) a
warmed persistent XLA compilation cache so cold start skips the first
compile.
"""

from __future__ import annotations

import sys
from pathlib import Path
from typing import TYPE_CHECKING

if TYPE_CHECKING:  # avoid circular import: buildengine.engine uses baselayer
    from lambdipy_tpu.buildengine.engine import BuildResult

from lambdipy_tpu.buildengine.vendor import vendor_distribution
from lambdipy_tpu.bundle.baselayer import base_layer_versions
from lambdipy_tpu.bundle.format import write_manifest
from lambdipy_tpu.recipes.schema import Recipe
from lambdipy_tpu.utils.fsutil import copy_tree
from lambdipy_tpu.utils.logs import get_logger, log_event

log = get_logger("lambdipy.package")

_HANDLER_TEMPLATE = '''\
"""Generated bundle entrypoint ({recipe}).

The serve runtime imports this module with the bundle site tree and base
layer on sys.path, calls ``init(ctx)`` once at boot (cold start), then
``invoke(state, request)`` per request.
"""

from {module} import {attr} as _build_handler

_SPEC = {spec!r}


def init(ctx):
    return _build_handler(_SPEC, ctx)


def invoke(state, request):
    return state.invoke(request)
'''


def materialize_payload(recipe: Recipe, bundle_dir: Path) -> dict:
    """Write the model payload into the bundle: generated handler.py and,
    for params="init", an orbax checkpoint of randomly initialized params
    (no weight-download path exists offline — SURVEY.md §8; real deployments
    pass a checkpoint path in payload.params)."""
    payload = recipe.payload
    assert payload is not None
    module, attr = payload.handler.split(":", 1)
    spec = {
        "recipe": recipe.name,
        "model": payload.model,
        "params": payload.params,
        "dtype": payload.dtype,
        "batch_size": payload.batch_size,
        "mesh": payload.mesh_dict(),
        "quant": payload.quant,
        "extra": dict(payload.extra),
        "device": recipe.device,
    }
    # a tokenizer named by the recipe is COPIED into the bundle (bundles
    # deploy on machines where the build-host path doesn't exist) and the
    # spec rewritten bundle-relative BEFORE it's baked into handler.py
    tok_path = spec["extra"].get("tokenizer_path")
    if tok_path:
        src = Path(tok_path)
        if not src.is_dir():
            raise ValueError(
                f"recipe {recipe.name}: tokenizer_path {tok_path!r} is not a directory")
        copy_tree(src, Path(bundle_dir) / "tokenizer")
        spec["extra"]["tokenizer_path"] = "tokenizer"
    handler_py = _HANDLER_TEMPLATE.format(
        recipe=recipe.name, module=module, attr=attr, spec=spec)
    (Path(bundle_dir) / "handler.py").write_text(handler_py)

    manifest_payload = dict(spec)
    if payload.params == "init" and payload.model not in ("hello",):
        from lambdipy_tpu.models import registry as model_registry

        params_dir = Path(bundle_dir) / "params"
        info = model_registry.save_init_params(
            payload.model, params_dir, dtype=payload.dtype, quant=payload.quant,
            extra=dict(payload.extra),
            params_format=payload.params_format)
        manifest_payload["params"] = "params"
        manifest_payload["params_info"] = info
    elif payload.params == "hf":
        # real weights: convert a local HuggingFace checkpoint
        # (payload.extra hf_path) into the bundle's orbax params
        from lambdipy_tpu.models.convert import save_hf_params

        hf_path = dict(payload.extra or ()).get("hf_path")
        if not hf_path:
            raise ValueError(
                f"recipe {recipe.name}: params='hf' needs [payload.extra] hf_path")
        info = save_hf_params(hf_path, Path(bundle_dir) / "params",
                              quant=payload.quant,
                              params_format=payload.params_format)
        manifest_payload["params"] = "params"
        manifest_payload["params_info"] = info
    elif payload.params not in ("init", "none", ""):
        # the schema's third form: a checkpoint PATH — either a params
        # dir written by save_checkpoint_files (orbax/ and/or params.fpk)
        # or a bare .fpk file. Every file is hardlinked when source and
        # bundle share a filesystem (an 8B fpk is ~8 GB; bundles never
        # mutate params), copied otherwise.
        import os
        import shutil

        def link_or_copy(s, d):
            try:
                os.link(s, d)
            except OSError:
                shutil.copy2(s, d)

        src = Path(payload.params)
        params_dir = Path(bundle_dir) / "params"
        if src.is_file() and src.suffix == ".fpk":
            params_dir.mkdir(parents=True, exist_ok=True)
            link_or_copy(src, params_dir / "params.fpk")
        elif src.is_dir() and ((src / "params.fpk").is_file()
                               or (src / "orbax").is_dir()):
            # validated up front: a typo'd-but-existing directory must
            # fail the BUILD, not the eventual serve boot
            shutil.copytree(src, params_dir, copy_function=link_or_copy)
        else:
            raise ValueError(
                f"recipe {recipe.name}: payload.params {payload.params!r} "
                "is neither 'init'/'hf', a params dir (params.fpk or "
                "orbax/ inside), nor a .fpk file")
        manifest_payload["params"] = "params"
        manifest_payload["params_info"] = {"format": "external",
                                           "source": str(src)}
    return manifest_payload


def assemble_bundle(result: "BuildResult", out_dir: Path, *,
                    plain_deps: list[str] | None = None,
                    with_payload: bool = True) -> dict:
    """Assemble the final bundle tree and write its manifest.

    ``plain_deps``: non-recipe project deps vendored straight into site/
    (the reference's "pip-install remaining deps into build/" step).
    Returns the manifest dict.
    """
    recipe = result.recipe
    out_dir = Path(out_dir)
    out_dir.mkdir(parents=True, exist_ok=True)
    site_dst = out_dir / "site"
    if result.site_dir.resolve() != site_dst.resolve():
        copy_tree(result.site_dir, site_dst)
    for dep in plain_deps or []:
        result.vendored.append(vendor_distribution(dep, site_dst))

    manifest_payload = None
    if with_payload and recipe.is_model:
        manifest_payload = materialize_payload(recipe, out_dir)

    manifest = write_manifest(
        out_dir,
        artifact_id=recipe.artifact_id(f"{sys.version_info.major}.{sys.version_info.minor}"),
        provenance=result.provenance(),
        base_layer={
            "name": recipe.base_layer,
            "versions": base_layer_versions(recipe.base_layer),
        },
        payload=manifest_payload,
        runtime={"entry": "handler.py"} if recipe.is_model else {},
    )
    log_event(log, "bundle assembled", recipe=recipe.name, out=str(out_dir),
              files=len(manifest["files"]))
    return manifest
