"""Recipe store: discovery and lookup of recipe documents.

Mirrors the reference's in-repo recipe directory (SURVEY.md §3.1 #3) —
builtin recipes live as TOML files in ``lambdipy_tpu/recipes/builtin/``;
additional stores (a project-local ``recipes/`` dir) can be layered on top.
"""

from __future__ import annotations

from functools import lru_cache
from pathlib import Path

from lambdipy_tpu.recipes.schema import Recipe, RecipeError, load_recipe_file

BUILTIN_DIR = Path(__file__).parent / "builtin"


class RecipeStore:
    def __init__(self, dirs: list[Path]):
        self._dirs = [Path(d) for d in dirs]
        self._recipes: dict[str, Recipe] = {}
        for d in self._dirs:
            if not d.is_dir():
                continue
            for path in sorted(d.glob("*.toml")):
                recipe = load_recipe_file(path)
                # later dirs override earlier ones (project overrides builtin)
                self._recipes[recipe.name] = recipe

    def names(self) -> list[str]:
        return sorted(self._recipes)

    def get(self, name: str) -> Recipe:
        try:
            return self._recipes[name]
        except KeyError:
            raise RecipeError(
                f"no recipe named {name!r}; available: {', '.join(self.names())}"
            ) from None

    def __contains__(self, name: str) -> bool:
        return name in self._recipes

    def covering(self, package: str) -> Recipe | None:
        """Recipe covering a plain pip package name, if any (used by the
        resolver to split recipe-covered vs plain deps, SURVEY.md §4 A)."""
        from packaging.utils import canonicalize_name

        return self._recipes.get(canonicalize_name(package))


@lru_cache(maxsize=None)
def builtin_store(extra_dir: str | None = None) -> RecipeStore:
    dirs = [BUILTIN_DIR]
    if extra_dir:
        dirs.append(Path(extra_dir))
    return RecipeStore(dirs)
