"""The elastic control plane: pure policy tables, hysteresis/cooldown
damping, the live-floor fuzz invariant, the controller's actuation vs
dry-run split, the router's fleet-level queue-wait fold, and the
scheduler's per-ticket wait stamp.

Everything here is in-process and fake-backed: the policy is a pure
function of (Snapshot, PolicyState, PolicyConfig) so the tables need no
servers, and the controller is exercised against a fake pool/router
that records actuator calls. The end-to-end loop (real subprocess
replicas, a real spike, the P99 recovery gate) lives in
``bench.py --autoscale``.
"""

from __future__ import annotations

import threading

import numpy as np
import pytest

from lambdipy_tpu.fleet import (DECODE, MIXED, PREFILL, FleetController,
                                PolicyConfig, PolicyState, ReplicaView,
                                Snapshot, decide)
from lambdipy_tpu.fleet.policy import (DEMOTE, PROMOTE, RETIRE, ROUTER,
                                       SET_KNOB, SPAWN)
from lambdipy_tpu.fleet.router import FleetRouter
from lambdipy_tpu.sched import SchedConfig, Scheduler


def _cfg(**kw) -> PolicyConfig:
    """A config tuned for one-tick tables: no sustain, no cooldown —
    each test re-adds exactly the damper it is about."""
    base = dict(slo_p99_ms=100.0, slo_class="interactive",
                hysteresis=0.25, sustain_s=0.0,
                lifecycle_cooldown_s=0.0, knob_cooldown_s=0.0,
                live_floor=1, min_replicas=1, max_replicas=8,
                max_prefill=2, util_low=0.25)
    base.update(kw)
    return PolicyConfig(**base)


def _snap(t, roles, *, p99=None, util=None, can_spawn=False,
          outstanding=None, managed=True, **kw) -> Snapshot:
    views = tuple(
        ReplicaView(name=f"r{i}", role=role, managed=managed,
                    outstanding=0 if outstanding is None
                    else outstanding[i])
        for i, role in enumerate(roles))
    return Snapshot(
        t=float(t), replicas=views,
        queue_wait_p99_ms={} if p99 is None else {"interactive": p99},
        util=util or {}, can_spawn=can_spawn, **kw)


# -- lifecycle decision tables ------------------------------------------------


@pytest.mark.parametrize("roles,p99,util,can_spawn,expect", [
    # sustained breach + a mixed replica to carve out -> promote
    ([MIXED, MIXED], 900.0, {}, False, (PROMOTE, "r0", PREFILL)),
    # breach but the prefill quota is full -> spawn is the fallback
    ([PREFILL, PREFILL, MIXED], 900.0, {}, True, (SPAWN, "", MIXED)),
    # breach, nothing mixed to promote, no spawner -> nothing
    ([DECODE, PREFILL], 900.0, {}, False, None),
    # breach but promoting the only decode-server would cross the
    # floor -> spawn instead
    ([MIXED, PREFILL], 900.0, {}, True, (SPAWN, "", MIXED)),
    # sustained all-clear + an idle prefill replica -> demote it back
    ([MIXED, PREFILL], 10.0, {PREFILL: 0.0}, False,
     (DEMOTE, "r1", MIXED)),
    # all-clear but the prefill class is busy -> keep it
    ([MIXED, PREFILL], 10.0, {PREFILL: 0.9, MIXED: 0.9}, False, None),
    # all-clear + an idle managed fleet above min -> retire one
    ([MIXED, MIXED], 10.0, {MIXED: 0.01}, False, (RETIRE, "r0", None)),
    # inside the hysteresis band: no evidence either way
    ([MIXED, MIXED], 100.0, {}, True, None),
    # no samples at all: never act on a guess
    ([MIXED, MIXED], None, {}, True, None),
])
def test_lifecycle_table(roles, p99, util, can_spawn, expect):
    cfg = _cfg(live_floor=1 if len(roles) > 1 else 0)
    state = PolicyState()
    acts = [a for a in decide(_snap(1.0, roles, p99=p99, util=util,
                                    can_spawn=can_spawn), state, cfg)
            if a.kind != SET_KNOB]
    if expect is None:
        assert acts == []
    else:
        kind, target, role = expect
        assert len(acts) == 1
        assert (acts[0].kind, acts[0].target, acts[0].role) == \
            (kind, target, role)


def test_promote_picks_least_outstanding_mixed():
    state = PolicyState()
    acts = decide(_snap(1.0, [MIXED, MIXED, MIXED], p99=900.0,
                        outstanding=[5, 0, 2]), state, _cfg())
    assert acts[0].kind == PROMOTE and acts[0].target == "r1"


def test_retire_skips_busy_and_unmanaged():
    # r0 busy, r1 idle-but-attached (unmanaged): nothing retirable
    state = PolicyState()
    views = (ReplicaView("r0", role=MIXED, managed=True, outstanding=3),
             ReplicaView("r1", role=MIXED, managed=False))
    snap = Snapshot(t=1.0, replicas=views,
                    queue_wait_p99_ms={"interactive": 10.0},
                    util={MIXED: 0.0})
    assert decide(snap, state, _cfg()) == []


def test_min_replicas_blocks_retire():
    state = PolicyState()
    acts = decide(_snap(1.0, [MIXED], p99=10.0, util={MIXED: 0.0}),
                  state, _cfg(min_replicas=1, live_floor=1))
    assert acts == []


# -- hysteresis + cooldown ----------------------------------------------------


def test_hysteresis_band_straddle_never_acts():
    """A P99 oscillating across the SLO line but inside the band
    sustains NEITHER timer: many ticks, zero actions."""
    cfg = _cfg(sustain_s=1.0)
    state = PolicyState()
    out = []
    for tick in range(60):
        p99 = 110.0 if tick % 2 else 90.0  # band is [75, 125]
        out += decide(_snap(tick * 0.5, [MIXED, MIXED], p99=p99,
                            can_spawn=True), state, cfg)
    assert [a for a in out if a.kind != SET_KNOB] == []


def test_hysteresis_flapping_signal_never_sustains():
    """Alternating hard-breach / hard-clear resets the opposite timer
    every tick, so with sustain > tick interval nothing ever fires."""
    cfg = _cfg(sustain_s=1.0)
    state = PolicyState()
    out = []
    for tick in range(60):
        p99 = 900.0 if tick % 2 else 5.0
        out += decide(_snap(tick * 0.5, [MIXED, MIXED], p99=p99,
                            util={PREFILL: 0.0, MIXED: 0.0},
                            can_spawn=True), state, cfg)
    assert [a for a in out if a.kind != SET_KNOB] == []


def test_sustain_then_promote():
    cfg = _cfg(sustain_s=1.0)
    state = PolicyState()
    assert decide(_snap(0.0, [MIXED, MIXED], p99=900.0), state,
                  cfg) == []
    assert decide(_snap(0.5, [MIXED, MIXED], p99=900.0), state,
                  cfg) == []
    acts = decide(_snap(1.0, [MIXED, MIXED], p99=900.0), state, cfg)
    assert [a.kind for a in acts] == [PROMOTE]


def test_lifecycle_cooldown_one_action_per_window():
    cfg = _cfg(lifecycle_cooldown_s=10.0)
    state = PolicyState()
    acts = decide(_snap(0.0, [MIXED, MIXED, MIXED], p99=900.0), state,
                  cfg)
    assert [a.kind for a in acts] == [PROMOTE]
    # the breach persists, but the cooldown holds the loop still
    for t in (1.0, 5.0, 9.9):
        assert decide(_snap(t, [PREFILL, MIXED, MIXED], p99=900.0),
                      state, cfg) == []
    # window over -> the next promote is allowed (quota has room)
    acts = decide(_snap(10.0, [PREFILL, MIXED, MIXED], p99=900.0),
                  state, cfg)
    assert [a.kind for a in acts] == [PROMOTE]


# -- knob rules ---------------------------------------------------------------


def _knob_views(**kw):
    base = dict(name="r0", role=MIXED, pipeline_depth=2,
                overlap_ratio=0.5, fetch_frac=0.1, spec_k=None,
                acceptance=None)
    base.update(kw)
    return (ReplicaView(**base),)


def _knob_snap(t, views, **kw):
    return Snapshot(t=float(t), replicas=views, **kw)


def test_depth_deepens_on_fetch_stall():
    acts = decide(_knob_snap(1.0, _knob_views(fetch_frac=0.4,
                                              overlap_ratio=0.6)),
                  PolicyState(), _cfg())
    assert [(a.kind, a.knob, a.value) for a in acts] == \
        [(SET_KNOB, "pipeline_depth", 3)]


def test_depth_shrinks_when_fetch_is_free():
    acts = decide(_knob_snap(1.0, _knob_views(fetch_frac=0.001)),
                  PolicyState(), _cfg())
    assert [(a.knob, a.value) for a in acts] == [("pipeline_depth", 1)]


def test_depth_holds_inside_band_and_at_bounds():
    # inside the band: nothing
    assert decide(_knob_snap(1.0, _knob_views(fetch_frac=0.1)),
                  PolicyState(), _cfg()) == []
    # stalled but already at depth_max: nothing
    assert decide(_knob_snap(1.0, _knob_views(fetch_frac=0.4,
                                              pipeline_depth=4)),
                  PolicyState(), _cfg()) == []
    # free but already at depth_min: nothing
    assert decide(_knob_snap(1.0, _knob_views(fetch_frac=0.001,
                                              pipeline_depth=1)),
                  PolicyState(), _cfg()) == []


def test_spec_k_resizes_on_acceptance_but_never_enables():
    # high acceptance widens to the next pow-2
    acts = decide(_knob_snap(1.0, _knob_views(spec_k=4,
                                              acceptance=0.95)),
                  PolicyState(), _cfg())
    assert [(a.knob, a.value) for a in acts] == [("spec_k", 8)]
    # low acceptance narrows
    acts = decide(_knob_snap(1.0, _knob_views(spec_k=4,
                                              acceptance=0.1)),
                  PolicyState(), _cfg())
    assert [(a.knob, a.value) for a in acts] == [("spec_k", 2)]
    # spec off (k unpublished or < 2): the policy never turns it on
    for k in (None, 0, 1):
        assert decide(_knob_snap(1.0, _knob_views(spec_k=k,
                                                  acceptance=0.95)),
                      PolicyState(), _cfg()) == []


def test_ship_window_tracks_ship_latency():
    cfg = _cfg()
    # slow transport -> widen (pow-2 step)
    acts = decide(Snapshot(t=1.0, ships=10, ship_ms_ewma=80.0,
                           ship_window=4), PolicyState(), cfg)
    assert [(a.target, a.knob, a.value) for a in acts] == \
        [(ROUTER, "ship_window", 8)]
    # near-free transport -> narrow
    acts = decide(Snapshot(t=1.0, ships=10, ship_ms_ewma=1.0,
                           ship_window=8), PolicyState(), cfg)
    assert [(a.value) for a in acts] == [4]
    # no ships yet: the EWMA has priced nothing — leave it alone
    assert decide(Snapshot(t=1.0, ships=0, ship_ms_ewma=80.0,
                           ship_window=4), PolicyState(), cfg) == []


def test_knob_cooldown_is_per_target_knob_pair():
    cfg = _cfg(knob_cooldown_s=5.0)
    state = PolicyState()
    views = (ReplicaView("a", pipeline_depth=2, overlap_ratio=0.5,
                         fetch_frac=0.4),
             ReplicaView("b", pipeline_depth=2, overlap_ratio=0.5,
                         fetch_frac=0.4))
    acts = decide(Snapshot(t=0.0, replicas=views), state, cfg)
    assert sorted(a.target for a in acts) == ["a", "b"]  # independent
    # both pairs are now cooling: an immediate re-tick emits nothing
    assert decide(Snapshot(t=1.0, replicas=views), state, cfg) == []
    # cooldown over: both retune again
    acts = decide(Snapshot(t=5.0, replicas=views), state, cfg)
    assert sorted(a.target for a in acts) == ["a", "b"]


# -- determinism + the live-floor fuzz ---------------------------------------


def test_decide_is_a_pure_function_of_its_inputs():
    """The same snapshot sequence through two fresh states renders the
    same actions byte-for-byte — the bench's replay gate, pure-level."""
    rng = np.random.default_rng(7)
    snaps = []
    for tick in range(40):
        roles = [MIXED, MIXED, PREFILL][:int(rng.integers(1, 4))]
        snaps.append(_snap(
            tick * 0.5, roles,
            p99=float(rng.choice([5.0, 100.0, 900.0])),
            util={PREFILL: float(rng.random()),
                  MIXED: float(rng.random())},
            can_spawn=bool(rng.integers(0, 2))))
    cfg = _cfg(sustain_s=1.0, lifecycle_cooldown_s=2.0)
    traces = []
    for _ in range(2):
        state = PolicyState()
        traces.append([a.render() for s in snaps
                       for a in decide(s, state, cfg)])
    assert traces[0] == traces[1]


@pytest.mark.parametrize("seed", range(5))
def test_fuzz_no_sequence_crosses_the_live_floor(seed):
    """Seeded random signals + faithfully applied decisions: the
    routable decode-serving count must never drop below live_floor, no
    matter what the sequence does."""
    rng = np.random.default_rng(seed)
    cfg = _cfg(sustain_s=1.0, lifecycle_cooldown_s=2.0,
               util_low=0.6, max_prefill=2, min_replicas=1,
               live_floor=1)
    state = PolicyState()
    fleet = [{"name": f"r{i}", "role": MIXED} for i in range(3)]
    spawned = 0
    for tick in range(300):
        views = tuple(
            ReplicaView(name=f["name"], role=f["role"], managed=True,
                        outstanding=int(rng.integers(0, 3)))
            for f in fleet)
        snap = Snapshot(
            t=tick * 0.7, replicas=views,
            queue_wait_p99_ms={
                "interactive": float(rng.choice([5.0, 900.0]))},
            util={PREFILL: float(rng.random()),
                  DECODE: float(rng.random()),
                  MIXED: float(rng.random())},
            can_spawn=bool(rng.integers(0, 2)))
        for a in decide(snap, state, cfg):
            if a.kind == PROMOTE:
                next(f for f in fleet
                     if f["name"] == a.target)["role"] = PREFILL
            elif a.kind == DEMOTE:
                next(f for f in fleet
                     if f["name"] == a.target)["role"] = MIXED
            elif a.kind == RETIRE:
                fleet = [f for f in fleet if f["name"] != a.target]
            elif a.kind == SPAWN:
                fleet.append({"name": f"s{spawned}", "role": MIXED})
                spawned += 1
        serving = [f for f in fleet
                   if f["role"] in (DECODE, MIXED)]
        assert len(serving) >= cfg.live_floor, \
            f"tick {tick}: fleet {fleet} crossed the floor"


# -- the controller against a fake pool/router --------------------------------


class FakeReplica:
    def __init__(self, name, role=MIXED, managed=True):
        self.name, self.role = name, role
        self.routable, self.managed = True, managed
        self.outstanding, self.state = 0, "ready"


class FakePool:
    def __init__(self, replicas):
        self._lock = threading.Lock()
        self.replicas = {r.name: r for r in replicas}
        self.calls: list = []

    def set_role(self, name, role, *, reship=True):
        self.calls.append(("set_role", name, role))
        self.replicas[name].role = role

    def retire(self, name, *, grace=10.0):
        self.calls.append(("retire", name))
        self.replicas[name].state = "stopped"


class FakeRouter:
    def __init__(self, pool, metrics):
        self.pool = pool
        self._metrics = metrics
        self.ship_window = 4

    def metrics(self):
        if isinstance(self._metrics, Exception):
            raise self._metrics
        return self._metrics() if callable(self._metrics) \
            else self._metrics


def _breach_metrics(p99=900.0):
    return {"fleet": {"queue_wait": {
        "interactive": {"count": 9, "p50_ms": p99 / 2,
                        "p99_ms": p99}}}}


def test_controller_tick_applies_promote_and_logs_the_event():
    pool = FakePool([FakeReplica("a"), FakeReplica("b")])
    router = FakeRouter(pool, _breach_metrics())
    ctrl = FleetController(router, config=_cfg(), interval_s=99)
    assert router.controller is ctrl  # /metrics registration
    acts = ctrl.tick()
    assert [a.kind for a in acts] == [PROMOTE]
    assert pool.calls == [("set_role", "a", PREFILL)]
    assert pool.replicas["a"].role == PREFILL
    rep = ctrl.report()
    assert rep["actions"] == {PROMOTE: 1} and rep["intents"] == {}
    # nemesis event grammar: "@T action target"
    assert len(ctrl.events) == 1
    ev = ctrl.events[0]["event"]
    assert ev.startswith("@") and " promote a" in ev
    assert rep["last_decision"]["applied"] is True


def test_controller_dry_run_logs_intents_but_touches_nothing():
    pool = FakePool([FakeReplica("a"), FakeReplica("b")])
    router = FakeRouter(pool, _breach_metrics())
    ctrl = FleetController(router, config=_cfg(), interval_s=99,
                           dry_run=True)
    acts = ctrl.tick()
    assert [a.kind for a in acts] == [PROMOTE]
    assert pool.calls == [] and ctrl.events == []
    assert pool.replicas["a"].role == MIXED
    rep = ctrl.report()
    assert rep["intents"] == {PROMOTE: 1} and rep["actions"] == {}
    assert rep["dry_run"] is True
    assert rep["last_decision"]["applied"] is False


def test_controller_scrape_failure_skips_the_tick():
    pool = FakePool([FakeReplica("a")])
    router = FakeRouter(pool, RuntimeError("replica down"))
    ctrl = FleetController(router, config=_cfg(), interval_s=99)
    assert ctrl.tick() == []
    rep = ctrl.report()
    assert rep["errors"] == 1 and rep["actions"] == {}
    assert pool.calls == []


def test_controller_sets_the_router_ship_window():
    pool = FakePool([FakeReplica("a")])
    router = FakeRouter(pool, {"fleet": {"disagg": {
        "ships": 10, "ship_ms_ewma": 80.0}}})
    ctrl = FleetController(router, config=_cfg(), interval_s=99)
    acts = ctrl.tick()
    assert [(a.kind, a.knob) for a in acts] == [(SET_KNOB,
                                                 "ship_window")]
    assert router.ship_window == 8
    assert ctrl.report()["targets"]["ship_window"] == 8


def test_controller_replay_is_byte_identical():
    pool = FakePool([FakeReplica("a"), FakeReplica("b"),
                     FakeReplica("c")])
    seq = iter([900.0, 900.0, 5.0, 5.0, 900.0])
    router = FakeRouter(pool,
                        lambda: _breach_metrics(next(seq, 50.0)))
    ctrl = FleetController(router, config=_cfg(sustain_s=0.0,
                                               lifecycle_cooldown_s=0.0),
                           interval_s=99)
    for _ in range(5):
        ctrl.tick()
    assert len(ctrl.decision_log) == 5
    assert ctrl.replay_decisions() is True


def test_controller_retired_replica_leaves_the_snapshot():
    pool = FakePool([FakeReplica("a"), FakeReplica("b")])
    pool.replicas["b"].state = "stopped"
    router = FakeRouter(pool, {"fleet": {}})
    ctrl = FleetController(router, config=_cfg(), interval_s=99)
    snap = ctrl.build_snapshot(router.metrics())
    assert [r.name for r in snap.replicas] == ["a"]


# -- the router's fleet-level queue-wait fold ---------------------------------


def test_fold_queue_wait_aggregates_per_class():
    per = {
        "r0": {"sched": {"queue_wait": {
            "interactive": {"count": 10, "p50_ms": 10.0,
                            "p99_ms": 100.0}}}},
        "r1": {"sched": {"queue_wait": {
            "interactive": {"count": 30, "p50_ms": 20.0,
                            "p99_ms": 50.0},
            "batch": {"count": 4, "p50_ms": 5.0, "p99_ms": 9.0}}}},
        "r2": {"error": "unreachable"},
    }
    out = FleetRouter._fold_queue_wait(per)
    # counts sum; p50 is the count-weighted mean; p99 is the max
    # (a sound upper bound on the union's p99)
    assert out["interactive"] == {"count": 40, "p50_ms": 17.5,
                                  "p99_ms": 100.0}
    assert out["batch"] == {"count": 4, "p50_ms": 5.0, "p99_ms": 9.0}
    assert FleetRouter._fold_queue_wait({}) == {}


# -- the scheduler's per-ticket wait stamp ------------------------------------


def test_scheduler_stamps_wait_ms_at_grant():
    s = Scheduler(SchedConfig(max_concurrency=1))
    t = s.admit()
    assert s.wait_turn(t, timeout=5)
    assert t.wait_ms is not None and t.wait_ms >= 0.0
    s.finish(t)
    # a queued ticket's stamp reflects its actual wait, not admission
    t1 = s.admit()
    assert s.wait_turn(t1, timeout=5)
    t2 = s.admit()
    assert t2.wait_ms is None  # not yet granted
    s.finish(t1)
    assert s.wait_turn(t2, timeout=5)
    assert t2.wait_ms is not None and t2.wait_ms >= 0.0
    s.finish(t2)


# -- the max_logical_ctx retune (offload-stall damped rule) -------------------


def _lc_views(**kw):
    base = dict(name="r0", role=MIXED, max_logical_ctx=2048,
                compiled_window=128, boot_logical_ctx=2048,
                offload_stall_frac=0.0, prefetch_hit_rate=0.9)
    base.update(kw)
    return (ReplicaView(**base),)


def test_logical_ctx_halves_on_sustained_stalls():
    acts = decide(_knob_snap(1.0, _lc_views(offload_stall_frac=0.2)),
                  PolicyState(), _cfg())
    assert [(a.kind, a.knob, a.value) for a in acts] == \
        [(SET_KNOB, "max_logical_ctx", 1024)]
    assert "stall" in acts[0].reason


def test_logical_ctx_never_steps_below_the_compiled_window():
    # halving 200 would land at 100 — the floor is the window (128)
    acts = decide(_knob_snap(1.0, _lc_views(max_logical_ctx=200,
                                            offload_stall_frac=0.5)),
                  PolicyState(), _cfg())
    assert [(a.knob, a.value) for a in acts] == [("max_logical_ctx", 128)]
    # already at the window: stalls or not, nothing to shrink
    assert decide(_knob_snap(1.0, _lc_views(max_logical_ctx=128,
                                            offload_stall_frac=0.5)),
                  PolicyState(), _cfg()) == []


def test_logical_ctx_low_prefetch_corroborates_mid_band_stalls():
    # stalls inside the band alone: hold
    assert decide(_knob_snap(1.0, _lc_views(offload_stall_frac=0.05)),
                  PolicyState(), _cfg()) == []
    # same stalls + a collapsed prefetch hit rate: step down
    acts = decide(_knob_snap(1.0, _lc_views(offload_stall_frac=0.05,
                                            prefetch_hit_rate=0.2)),
                  PolicyState(), _cfg())
    assert [(a.knob, a.value) for a in acts] == [("max_logical_ctx",
                                                  1024)]
    # clean stalls: a bad hit rate alone never shrinks the window
    assert decide(_knob_snap(1.0, _lc_views(offload_stall_frac=0.01,
                                            prefetch_hit_rate=0.2)),
                  PolicyState(), _cfg()) == []


def test_logical_ctx_restores_on_clean_windows_capped_at_boot():
    # clean window, previously stepped down: double back up
    acts = decide(_knob_snap(1.0, _lc_views(max_logical_ctx=512,
                                            boot_logical_ctx=2048)),
                  PolicyState(), _cfg())
    assert [(a.knob, a.value) for a in acts] == [("max_logical_ctx",
                                                  1024)]
    # doubling past boot clamps to boot
    acts = decide(_knob_snap(1.0, _lc_views(max_logical_ctx=1536,
                                            boot_logical_ctx=2048)),
                  PolicyState(), _cfg())
    assert [(a.knob, a.value) for a in acts] == [("max_logical_ctx",
                                                  2048)]
    # at boot already: a clean window is the steady state, not a signal
    assert decide(_knob_snap(1.0, _lc_views()), PolicyState(),
                  _cfg()) == []


def test_logical_ctx_skips_unpublished_signals():
    # no long-context block on the replica: every field is None
    for missing in ("offload_stall_frac", "max_logical_ctx",
                    "compiled_window"):
        kw = {"offload_stall_frac": 0.5, missing: None}
        assert decide(_knob_snap(1.0, _lc_views(**kw)),
                      PolicyState(), _cfg()) == []


def test_logical_ctx_cooldown_damps_the_rule():
    cfg = _cfg(knob_cooldown_s=5.0)
    state = PolicyState()
    views = _lc_views(offload_stall_frac=0.5)
    acts = decide(_knob_snap(0.0, views), state, cfg)
    assert [(a.knob, a.value) for a in acts] == [("max_logical_ctx",
                                                  1024)]
    # still stalling one tick later: the cooldown holds the knob
    assert decide(_knob_snap(1.0, views), state, cfg) == []
    # cooldown over: the next halving lands
    acts = decide(_knob_snap(5.0, views), state, cfg)
    assert [(a.knob, a.value) for a in acts] == [("max_logical_ctx",
                                                  1024)]


def _lc_metrics(stall_s, *, wall_s=10.0, mlc=2048, hit=0.9):
    return {"replicas": {"a": {"handler": {"batching": {
        "pipeline": {"wall_s": wall_s},
        "long_context": {"stall_s": stall_s, "prefetch_hit_rate": hit,
                         "max_logical_ctx": mlc, "window": 128,
                         "boot_logical_ctx": 2048}}}}}}


def test_controller_retunes_logical_ctx_over_debug_knobs(monkeypatch):
    posts = []

    def fake_post(url, payload, timeout=None):
        posts.append((url, payload))
        return {"ok": True}

    monkeypatch.setattr("lambdipy_tpu.fleet.controller._http_json",
                        fake_post)
    pool = FakePool([FakeReplica("a")])
    pool.replicas["a"].url = "http://a:1"
    seq = iter([_lc_metrics(3.0),              # 30% stall -> halve
                _lc_metrics(3.0, mlc=1024),    # still hot -> halve again
                _lc_metrics(0.1, mlc=512),     # clean -> restore
                _lc_metrics(0.1, mlc=1024)])   # clean -> restore
    router = FakeRouter(pool, lambda: next(seq))
    ctrl = FleetController(router, config=_cfg(), interval_s=99)
    for _ in range(4):
        ctrl.tick()
    assert posts == [("http://a:1/v1/debug/knobs",
                      {"max_logical_ctx": v})
                     for v in (1024, 512, 1024, 2048)]
    # the recorded decisions replay byte-for-byte
    assert len(ctrl.decision_log) == 4
    assert ctrl.replay_decisions() is True
