"""Data pipeline: windowing, deterministic shuffling, resume equivalence,
process sharding, mesh placement, and integration with the train step +
checkpoint (the full resumable-training loop)."""

import numpy as np
import pytest

from lambdipy_tpu.data import ShardedLoader, TokenSource


def _source(n_tokens=1000, seq_len=8):
    return TokenSource(np.arange(n_tokens, dtype=np.int32), seq_len)


def test_token_source_windows():
    src = TokenSource(np.arange(100, dtype=np.int32), seq_len=9)
    assert len(src) == 11  # starts 0, 9, ..., 90 (stride = seq_len)
    np.testing.assert_array_equal(src[0], np.arange(10))
    np.testing.assert_array_equal(src[1], np.arange(9, 19))  # +1 overlap


def test_token_source_stride_and_files(tmp_path):
    src = TokenSource(np.arange(100, dtype=np.int32), seq_len=9, stride=5)
    np.testing.assert_array_equal(src[1], np.arange(5, 15))

    npy = tmp_path / "toks.npy"
    np.save(npy, np.arange(64, dtype=np.int32))
    from_npy = TokenSource(npy, seq_len=7)
    np.testing.assert_array_equal(from_npy[0], np.arange(8))

    raw = tmp_path / "toks.bin"
    np.arange(64, dtype=np.int32).tofile(raw)
    from_raw = TokenSource(raw, seq_len=7)
    np.testing.assert_array_equal(from_raw[1], from_npy[1])


def test_token_source_validation():
    with pytest.raises(ValueError):
        TokenSource(np.zeros((2, 2), np.int32), seq_len=4)
    with pytest.raises(ValueError):
        TokenSource(np.arange(4, dtype=np.int32), seq_len=8)


def test_loader_deterministic_and_epoch_reshuffle():
    a = ShardedLoader(_source(), 4, seed=1, process_index=0, process_count=1)
    b = ShardedLoader(_source(), 4, seed=1, process_index=0, process_count=1)
    for _ in range(3):
        np.testing.assert_array_equal(a.next_batch(), b.next_batch())

    # different seed -> different order; next epoch -> different order
    c = ShardedLoader(_source(), 4, seed=2, process_index=0, process_count=1)
    assert not np.array_equal(a.next_batch(), c.next_batch())
    first_epoch0 = ShardedLoader(_source(), 4, seed=1, process_index=0,
                                 process_count=1).next_batch()
    d = ShardedLoader(_source(), 4, seed=1, process_index=0, process_count=1)
    for _ in range(d.steps_per_epoch):
        d.next_batch()
    assert d.state.step_in_epoch == d.steps_per_epoch
    first_epoch1 = d.next_batch()
    assert d.state.epoch == 1
    assert not np.array_equal(first_epoch0, first_epoch1)


def test_loader_resume_replays_exact_sequence():
    a = ShardedLoader(_source(), 4, seed=7, process_index=0, process_count=1)
    for _ in range(5):
        a.next_batch()
    snapshot = a.state_dict()
    expected = [a.next_batch() for _ in range(4)]

    b = ShardedLoader(_source(), 4, seed=0, process_index=0, process_count=1)
    b.restore(snapshot)
    got = [b.next_batch() for _ in range(4)]
    for e, g in zip(expected, got):
        np.testing.assert_array_equal(e, g)


def test_loader_process_sharding_partitions_global_batch():
    """Two processes' shards concatenate to the single-process batch."""
    whole = ShardedLoader(_source(), 8, seed=3, process_index=0, process_count=1)
    p0 = ShardedLoader(_source(), 8, seed=3, process_index=0, process_count=2)
    p1 = ShardedLoader(_source(), 8, seed=3, process_index=1, process_count=2)
    for _ in range(3):
        w = whole.next_batch()
        np.testing.assert_array_equal(
            w, np.concatenate([p0.next_batch(), p1.next_batch()]))
    with pytest.raises(ValueError):
        ShardedLoader(_source(), 9, process_index=0, process_count=2)


def test_loader_place_on_mesh(cpu_devices):
    import jax
    from lambdipy_tpu.parallel.mesh import make_mesh

    loader = ShardedLoader(_source(seq_len=16), 8, seed=0,
                           process_index=0, process_count=1)
    mesh = make_mesh({"dp": 4, "sp": 2})
    batch = loader.next_batch()
    arr = loader.place(batch, mesh)
    assert arr.shape == (8, 17)
    assert len(arr.sharding.device_set) == 8
    np.testing.assert_array_equal(np.asarray(jax.device_get(arr)), batch)


def test_loader_train_checkpoint_roundtrip(tmp_path, cpu_devices):
    """Loader state rides the orbax checkpoint next to the train state; a
    resumed run consumes exactly the batches the original would have."""
    import jax
    from lambdipy_tpu.models import registry
    from lambdipy_tpu.parallel.mesh import make_mesh, use_mesh
    from lambdipy_tpu.train.checkpoint import TrainCheckpointer
    from lambdipy_tpu.train.step import sharded_train_step

    adapter = registry.get("llama-tiny").build()
    params = adapter.init_params(seed=0)
    mesh = make_mesh({"dp": 2}, devices=jax.devices()[:2])
    src = TokenSource(
        np.random.default_rng(0).integers(0, 500, 2000).astype(np.int32),
        seq_len=16)
    loader = ShardedLoader(src, 4, seed=5, process_index=0, process_count=1)

    with use_mesh(mesh):
        step, state, batch_sharding = sharded_train_step(
            adapter.forward, params, mesh, adapter.tp_rules)
        with TrainCheckpointer(tmp_path / "ck") as ckpt:
            for i in range(1, 3):
                batch = loader.place(loader.next_batch(), mesh, batch_sharding)
                state, _ = step(state, batch)
                ckpt.save(i, {"train": state, "loader": loader.state_dict()})
        expected_next = loader.next_batch()

    ck2 = TrainCheckpointer(tmp_path / "ck")
    with use_mesh(mesh):
        _, state2, _ = sharded_train_step(
            adapter.forward, params, mesh, adapter.tp_rules)
        restored, at = ck2.restore({"train": state2, "loader": loader.state_dict()})
    assert at == 2
    loader2 = ShardedLoader(src, 4, seed=0, process_index=0, process_count=1)
    loader2.restore(jax.tree_util.tree_map(int, restored["loader"]))
    np.testing.assert_array_equal(loader2.next_batch(), expected_next)
    ck2.close()
