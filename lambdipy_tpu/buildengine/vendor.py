"""Vendor backend: copy installed distributions into a bundle site tree.

The offline replacement for the reference's in-container ``pip install``
(SURVEY.md §4 A build path): the host env is the wheel store (SURVEY.md §8),
and a distribution's installed file list (``RECORD`` via
``importlib.metadata``) tells us exactly what to copy — the same ground
truth pip itself maintains.
"""

from __future__ import annotations

import importlib.metadata
import shutil
from pathlib import Path

from packaging.utils import canonicalize_name

from lambdipy_tpu.utils.logs import get_logger

log = get_logger("lambdipy.vendor")


class VendorError(RuntimeError):
    pass


def find_distribution(name: str) -> importlib.metadata.Distribution | None:
    try:
        return importlib.metadata.distribution(name)
    except importlib.metadata.PackageNotFoundError:
        return None


def import_names(dist: importlib.metadata.Distribution) -> list[str]:
    """Top-level import names for a distribution (scikit-learn -> sklearn).

    Prefers ``top_level.txt``; falls back to scanning the file list for
    top-level packages/modules.
    """
    try:
        text = dist.read_text("top_level.txt")
    except Exception:
        text = None
    if text:
        return [line.strip() for line in text.splitlines() if line.strip()]
    names: set[str] = set()
    for f in dist.files or []:
        parts = Path(str(f)).parts
        if not parts or parts[0].endswith((".dist-info", ".data")) or parts[0] == "..":
            continue
        if len(parts) == 1:
            if parts[0].endswith(".py"):
                names.add(parts[0].removesuffix(".py"))
            elif ".so" in parts[0]:
                names.add(parts[0].split(".")[0])
        else:
            names.add(parts[0])
    # drop non-importable artifacts: "numpy.libs" (bundled .so dirs),
    # top-level __pycache__ from sloppy RECORDs
    return sorted(n for n in names if n and "." not in n and n != "__pycache__")


def dependency_closure(roots: list[str]) -> list[str]:
    """Transitive closure of installed distributions reachable from ``roots``.

    Roots may carry extras (``jax[tpu]``). Markers are evaluated against the
    running environment; extra-gated deps are followed only for requested
    extras. Distributions not installed locally are silently absent from the
    closure — the engine decides whether that is fatal (mandatory) or not
    (optional/base-layer-provided).
    """
    from packaging.markers import default_environment
    from packaging.requirements import Requirement as PepReq

    env_base = default_environment()
    seen: set[str] = set()
    visited: set[tuple[str, frozenset[str]]] = set()  # termination on extras cycles
    queue: list[tuple[str, frozenset[str]]] = []
    for root in roots:
        req = PepReq(root) if any(c in root for c in "[<>=!~;") else None
        if req is not None:
            queue.append((canonicalize_name(req.name), frozenset(req.extras)))
        else:
            queue.append((canonicalize_name(root), frozenset()))
    while queue:
        cname, extras = queue.pop()
        if (cname, extras) in visited:
            continue
        visited.add((cname, extras))
        dist = find_distribution(cname)
        if dist is None:
            continue
        seen.add(cname)
        for req_str in dist.requires or []:
            req = PepReq(req_str)
            if req.marker is not None:
                ok = any(
                    req.marker.evaluate({**env_base, "extra": e})
                    for e in (extras or {""})
                )
                if not ok:
                    continue
            queue.append((canonicalize_name(req.name), frozenset(req.extras)))
    return sorted(seen)


def vendor_distribution(name: str, dest_site: Path) -> dict:
    """Copy one installed distribution's files into ``dest_site``.

    Returns a provenance record {name, version, n_files, bytes}. Raises
    :class:`VendorError` when the distribution is not installed.
    """
    dist = find_distribution(name)
    if dist is None:
        raise VendorError(
            f"distribution {name!r} is not installed in the local wheel store")
    dest_site = Path(dest_site)
    dest_site.mkdir(parents=True, exist_ok=True)
    n_files = 0
    n_bytes = 0
    for f in dist.files or []:
        rel = Path(str(f))
        if rel.suffix == ".pyc" or "__pycache__" in rel.parts:
            continue
        # files outside site-packages (console scripts in ../../../bin) are
        # not part of an importable bundle — skip, like the reference's
        # artifact tars which carry only the package tree
        if rel.parts and rel.parts[0] == "..":
            continue
        src = Path(dist.locate_file(f))
        if not src.is_file():
            continue
        dst = dest_site / rel
        dst.parent.mkdir(parents=True, exist_ok=True)
        shutil.copy2(src, dst, follow_symlinks=True)
        n_files += 1
        n_bytes += dst.stat().st_size
    if n_files == 0:
        raise VendorError(f"distribution {name!r} has no copyable files (no RECORD?)")
    return {
        "name": canonicalize_name(name),
        "version": dist.version,
        "files": n_files,
        "bytes": n_bytes,
        "import_names": import_names(dist),
    }
