"""Device tests (SURVEY.md §5.3): the real chip, through the REAL serve
path — build the flagship bundle, deploy it, and assert the north-star
budgets (BASELINE.json: ResNet-50 < 15 ms p50, < 10 s cold start).

Marked ``tpu`` and deselected by default (pyproject addopts): the suite's
conftest pins the in-process platform to CPU, so these tests do all jax
work in subprocesses with the shell's device platform — which also guards
against the axon tunnel's observed wedge (a probe with a timeout decides
skip vs run). Run with: ``pytest -m tpu --override-ini addopts=''``.
"""

import sys
from pathlib import Path

import pytest

sys.path.insert(0, str(Path(__file__).resolve().parents[1] / "scripts"))

pytestmark = pytest.mark.tpu


@pytest.fixture(scope="module")
def device_ok():
    from measure_baseline import tpu_reachable

    if not tpu_reachable():
        pytest.skip("TPU device unreachable (tunnel wedge or no device)")
    return True


def test_resnet50_serve_path_meets_north_star(device_ok, tmp_path):
    """Config 3 through build -> deploy -> HTTP invoke on the chip.

    The north-star p50 is asserted NET of the environment's measured
    device->host transport floor: this image reaches its chip through a
    remote-tunnel PJRT plugin where every fetch of a fresh device result
    pays one network RTT (~66 ms measured; h2d stays sub-ms), which no
    serving stack can engineer away from inside a synchronous invoke. On
    real locally-attached hardware the floor is ~0 and the assertion
    converges to the plain end-to-end budget."""
    from measure_baseline import measure_config, publish

    rec = measure_config(3, invokes=50, work=tmp_path)
    assert rec["platform"] not in ("cpu",), rec
    p50_net = rec.get("serve_overhead_p50_ms", rec["invoke_p50_ms"])
    assert p50_net < 15.0, rec                # BASELINE.json north star
    assert rec["cold_start_s"] < 10.0, rec    # cold-start budget
    publish({"config3": rec})


def test_bert_serve_path_on_device(device_ok, tmp_path):
    """Config 4 (jax BERT) boots and serves on the chip; latency recorded."""
    from measure_baseline import measure_config, publish

    rec = measure_config(4, invokes=30, work=tmp_path)
    assert rec["platform"] not in ("cpu",), rec
    p50_net = rec.get("serve_overhead_p50_ms", rec["invoke_p50_ms"])
    assert p50_net < 100.0, rec  # sanity bound, not the star
    publish({"config4": rec})


def test_pallas_kernels_on_device(device_ok):
    """The Pallas kernels (flash attention, blocked int8 matmul) compile
    through the remote Mosaic path and match their pure-jax references on
    the real chip within bf16 tolerance. CPU tests only ever run these in
    interpret mode; this is the one place the compiled kernels are
    numerics-checked on hardware."""
    import json
    import subprocess
    import sys as _sys

    code = (
        "import json, numpy as np, jax, jax.numpy as jnp\n"
        "from lambdipy_tpu.ops.attention import flash_attention, mha_reference\n"
        "from lambdipy_tpu.ops.quant import int8_matmul, int8_matmul_reference\n"
        "rng = np.random.default_rng(0)\n"
        "b, s, h, d = 1, 512, 4, 64\n"
        "q, k, v = (jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.bfloat16)\n"
        "           for _ in range(3))\n"
        "got = np.asarray(jax.device_get(jax.jit(\n"
        "    lambda q, k, v: flash_attention(q, k, v, causal=True))(q, k, v)),\n"
        "    np.float32)\n"
        "ref = np.asarray(jax.device_get(mha_reference(q, k, v, causal=True)),\n"
        "                 np.float32)\n"
        "flash_rel = float(np.abs(got - ref).max() / np.abs(ref).max())\n"
        "x = jnp.asarray(rng.standard_normal((256, 512)), jnp.bfloat16)\n"
        "wf = rng.standard_normal((512, 256)).astype(np.float32)\n"
        "sc = (np.abs(wf).max(0, keepdims=True) / 127.0).astype(np.float32)\n"
        "wi = np.round(wf / sc).astype(np.int8)\n"
        "g2 = np.asarray(jax.device_get(jax.jit(int8_matmul)(\n"
        "    x, jnp.asarray(wi), jnp.asarray(sc))), np.float32)\n"
        "r2 = np.asarray(jax.device_get(int8_matmul_reference(\n"
        "    x, jnp.asarray(wi), jnp.asarray(sc))), np.float32)\n"
        "int8_rel = float(np.abs(g2 - r2).max() / np.abs(r2).max())\n"
        "print(json.dumps({'platform': jax.default_backend(),\n"
        "                  'flash_rel': flash_rel, 'int8_rel': int8_rel}))\n"
    )
    import os
    from pathlib import Path as _Path

    env = dict(os.environ)
    env["PYTHONPATH"] = os.pathsep.join(
        [str(_Path(__file__).parents[1])]
        + [p for p in env.get("PYTHONPATH", "").split(os.pathsep) if p])
    proc = subprocess.run([_sys.executable, "-c", code], capture_output=True,
                          text=True, timeout=420, env=env)
    assert proc.returncode == 0, (proc.stdout + proc.stderr)[-800:]
    res = json.loads(proc.stdout.strip().splitlines()[-1])
    assert res["platform"] != "cpu", res
    assert res["flash_rel"] < 0.02, res
    assert res["int8_rel"] < 0.02, res


def test_llama_int8_generate_serve_path(device_ok, tmp_path):
    """Config 5's serve path (int8 weights + compile-once decode) on the
    chip, at the single-chip exemplar scale; the full 8B recipe's v5e-4
    sharding is proven by the CPU-mesh dryrun, whose evidence rides in
    the published record."""
    import subprocess
    import sys as _sys
    from pathlib import Path as _Path

    from measure_baseline import measure_config, publish

    rec = measure_config(5, invokes=20, work=tmp_path)
    assert rec["platform"] not in ("cpu",), rec
    assert rec.get("decode_tok_s", 0) > 50, rec  # sanity: real decode speed
    dry = subprocess.run(
        [_sys.executable, str(_Path(__file__).parents[1] / "__graft_entry__.py")],
        capture_output=True, text=True, timeout=600,
        env={**__import__("os").environ, "GRAFT_DRYRUN_DEVICES": "8"})
    assert dry.returncode == 0, (dry.stdout + dry.stderr)[-500:]
    lines = dry.stdout.strip().splitlines()
    rec["multichip_dryrun"] = "pass: " + (lines[-1] if lines else "(no output)")
    publish({"config5": rec})
