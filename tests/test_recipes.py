"""Recipe schema + store tests (SURVEY.md §5 rebuild test plan, item 1)."""

import pytest

from lambdipy_tpu.recipes import (
    Recipe,
    RecipeError,
    builtin_store,
    load_recipe_dict,
    load_recipe_file,
)
from lambdipy_tpu.recipes.store import BUILTIN_DIR, RecipeStore


def test_builtin_recipes_all_load_and_validate():
    store = builtin_store()
    names = store.names()
    # the five baseline configs + package exemplars must be covered
    for expected in ["certifi", "numpy", "hello-numpy", "tabular-sklearn",
                     "jax-resnet50", "jax-bert", "torch-xla-bert", "jax-llama3-8b"]:
        assert expected in names, f"missing builtin recipe {expected}"
    for name in names:
        recipe = store.get(name)
        assert isinstance(recipe, Recipe)
        assert recipe.version


def test_model_recipes_have_payloads():
    store = builtin_store()
    for name in ["jax-resnet50", "jax-bert", "jax-llama3-8b", "hello-numpy"]:
        assert store.get(name).is_model
    assert not store.get("numpy").is_model
    llama = store.get("jax-llama3-8b")
    assert llama.payload.mesh_dict() == {"dp": 1, "tp": 4}
    assert llama.payload.quant == "int8"
    assert llama.device == "tpu-v5e-4"


def test_artifact_id_naming():
    r = builtin_store().get("jax-resnet50")
    assert r.artifact_id("3.12") == "jax-resnet50-1.0.0-py312-tpu-v5e-1"


def test_unknown_keys_rejected():
    with pytest.raises(RecipeError, match="unknown recipe keys"):
        load_recipe_dict({"name": "x", "version": "1", "bogus": True})


def test_bad_device_rejected():
    with pytest.raises(RecipeError, match="unknown device"):
        load_recipe_dict({"name": "x", "version": "1", "device": "gpu-h100"})


def test_sdist_requires_source():
    with pytest.raises(RecipeError, match="sdist build needs build.source"):
        load_recipe_dict({"name": "x", "version": "1", "build": {"backend": "sdist"}})


def test_payload_handler_format_enforced():
    with pytest.raises(RecipeError, match="module:attr"):
        load_recipe_dict({
            "name": "x", "version": "1",
            "payload": {"model": "m", "handler": "no_colon_here"},
        })


def test_invalid_toml_reported_with_path(tmp_path):
    p = tmp_path / "bad.toml"
    p.write_text("name = [unclosed")
    with pytest.raises(RecipeError, match="invalid TOML"):
        load_recipe_file(p)


def test_project_store_overrides_builtin(tmp_path):
    (tmp_path / "numpy.toml").write_text(
        'schema = 1\nname = "numpy"\nversion = "9.9.9"\n'
    )
    store = RecipeStore([BUILTIN_DIR, tmp_path])
    assert store.get("numpy").version == "9.9.9"


def test_covering_canonicalizes_name():
    store = builtin_store()
    assert store.covering("NumPy") is not None
    assert store.covering("nonexistent-pkg") is None
