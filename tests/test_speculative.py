"""Speculative decoding (prompt-lookup drafts + chunked verification):
bitwise greedy parity, acceptance accounting, eos/logprob behavior."""

import numpy as np
import pytest

from lambdipy_tpu.models import registry
from lambdipy_tpu.models.llama import _lookup_draft


@pytest.fixture(scope="module")
def tiny_server():
    adapter = registry.get("llama-tiny").build()
    return adapter.make_server(adapter.init_params(seed=0))


def test_lookup_draft_follows_repeats():
    # ...5, 6, 7 appeared before; drafting after [5, 6, 7] proposes what
    # followed last time
    ctx = [1, 5, 6, 7, 8, 9, 2, 5, 6, 7]
    assert _lookup_draft(ctx, 3) == [8, 9, 2]
    # no match anywhere -> repeat the last token
    assert _lookup_draft([1, 2, 3], 3) == [3, 3, 3]
    # partial candidate padded with the last token
    assert _lookup_draft([4, 9, 9, 4], 3)[0] == 9


def test_lookup_draft_edge_cases():
    """Degenerate inputs the engine's draft loop can hand the lookup:
    empty context, contexts shorter than ngram_max, and the hit flag
    distinguishing a real n-gram match from the fallback."""
    from lambdipy_tpu.models.llama import _lookup_draft_hit

    # empty context: content-free zeros, never a crash (and never a
    # false hit — zeros are only proposals, the verify rejects them)
    assert _lookup_draft_hit([], 4) == ([0, 0, 0, 0], False)
    assert _lookup_draft([], 2) == [0, 0]
    # single-token context (shorter than any n-gram window): fallback
    assert _lookup_draft_hit([7], 3) == ([7, 7, 7], False)
    # two tokens, one repeat: the g=1 window still matches
    draft, hit = _lookup_draft_hit([9, 9], 3)
    assert hit and draft[0] == 9
    # context shorter than ngram_max but with a bigram repeat: matches
    # at the longest g that fits, not ngram_max; the candidate stops at
    # the context end and pads with the last token
    draft, hit = _lookup_draft_hit([5, 6, 5, 6], 4, ngram_max=3)
    assert hit and draft == [5, 6, 6, 6]
    # hit flag splits match from fallback
    assert _lookup_draft_hit([1, 2, 3], 3)[1] is False
    assert _lookup_draft_hit([1, 5, 6, 7, 8, 9, 2, 5, 6, 7], 3)[1] is True


def test_lookup_draft_proposes_eos(tiny_server):
    """A draft CONTAINING the eos token is proposed like any other (the
    lookup has no eos concept) and the verify path latches it with
    fused-path parity."""
    eos = 42
    ctx = [1, 5, 6, 7, eos, 9, 2, 5, 6, 7]
    assert _lookup_draft(ctx, 3) == [eos, 9, 2]
    # end-to-end: an eos the model actually emits inside an accepted
    # block truncates + fills exactly like the plain path
    free = tiny_server.generate([5, 6, 7, 8], max_new_tokens=12)[0]
    model_eos = int(free[5])
    ref = tiny_server.generate([5, 6, 7, 8], max_new_tokens=12,
                               eos_id=model_eos)
    out = tiny_server.generate_speculative([5, 6, 7, 8],
                                           max_new_tokens=12, k=8,
                                           eos_id=model_eos)
    np.testing.assert_array_equal(out, ref)


def test_speculative_k1_degenerates_to_plain(tiny_server):
    """k=1 (no real drafting room — the kb floor is a 2-chunk) must
    equal plain decode token for token, and the engine knob disables at
    spec_k <= 1 (k=1 IS the plain path)."""
    ref = tiny_server.generate([5, 6, 7, 8], max_new_tokens=12)
    out = tiny_server.generate_speculative([5, 6, 7, 8],
                                           max_new_tokens=12, k=1)
    np.testing.assert_array_equal(out, ref)
    from lambdipy_tpu.runtime.continuous import ContinuousBatcher

    cb = ContinuousBatcher(tiny_server, slots=2, segment=4, spec_k=1)
    assert cb.spec_k == 0
    np.testing.assert_array_equal(
        cb.generate([5, 6, 7, 8], max_new_tokens=12), ref)


def test_sp_decode_standdown_is_observable(cpu_devices):
    """ROADMAP direction-2 note: sp decode silently stood down under
    blocked attention. The condition now bumps the spec_standdown
    counter (one structured log line per distinct reason) and surfaces
    through SpecDecodeStats.report."""
    from lambdipy_tpu.models import registry
    from lambdipy_tpu.parallel import spdecode
    from lambdipy_tpu.parallel.mesh import make_mesh, use_mesh
    from lambdipy_tpu.runtime.metrics import SpecDecodeStats

    spdecode._reset_standdowns_for_tests()
    assert spdecode.standdown_count() == 0
    adapter = registry.get("llama-tiny").build(
        extra={"attn_backend": "blocked"})
    params = adapter.init_params(seed=0)
    server = adapter.make_server(params)
    mesh = make_mesh({"sp": 2}, devices=cpu_devices[:2])
    server.mesh = mesh
    with use_mesh(mesh):
        server.generate([1, 2, 3], max_new_tokens=1)
    n = spdecode.standdown_count()
    assert n > 0, "blocked-backend decode under an sp mesh must record"
    stats = spdecode.standdown_stats()
    assert stats["spec_standdown"] == n
    assert any(r.startswith("attn_backend=") for r in stats["reasons"])
    # mirrored onto the /metrics spec block
    assert SpecDecodeStats().report()["sp_standdown"] == n
    spdecode._reset_standdowns_for_tests()


def test_speculative_matches_plain_greedy(tiny_server):
    """The core guarantee: speculative output is BITWISE the plain greedy
    output for any k (drafts change the verification batching, never the
    chosen tokens)."""
    for prompt in ([1, 2, 3, 4, 5], [9, 8, 7], list(range(1, 30))):
        ref = tiny_server.generate(prompt, max_new_tokens=24)
        for k in (2, 4, 8):
            out = tiny_server.generate_speculative(
                prompt, max_new_tokens=24, k=k)
            np.testing.assert_array_equal(
                out, ref, err_msg=f"prompt={prompt[:3]}... k={k}")


def test_speculative_accepts_on_repetitive_decode(tiny_server):
    """Greedy decodes of the tiny model fall into cycles; once they do,
    prompt-lookup drafts verify several tokens per step — the counters
    must show >1 token per weight read."""
    out = tiny_server.generate([5, 6, 7, 8], max_new_tokens=48)
    spec = tiny_server.generate_speculative([5, 6, 7, 8],
                                            max_new_tokens=48, k=8)
    np.testing.assert_array_equal(spec, out)
    stats = tiny_server.spec_stats
    assert stats["emitted"] >= 48
    assert stats["tokens_per_step"] > 1.0, stats
    assert stats["steps"] < 48, stats


def test_speculative_eos_matches_fused_latch(tiny_server):
    free = tiny_server.generate([5, 6, 7, 8], max_new_tokens=10)[0]
    eos = int(free[3])
    ref = tiny_server.generate([5, 6, 7, 8], max_new_tokens=10, eos_id=eos)
    out = tiny_server.generate_speculative([5, 6, 7, 8], max_new_tokens=10,
                                           k=4, eos_id=eos)
    np.testing.assert_array_equal(out, ref)


def test_speculative_logprobs_match_plain(tiny_server):
    rt, rl = tiny_server.generate([1, 2, 3], max_new_tokens=12,
                                  return_logprobs=True)
    st, sl = tiny_server.generate_speculative([1, 2, 3], max_new_tokens=12,
                                              k=4, return_logprobs=True)
    np.testing.assert_array_equal(st, rt)
    np.testing.assert_allclose(sl, rl, rtol=1e-4, atol=1e-4)


def test_speculative_near_window_falls_back(tiny_server):
    """No room for a verify chunk near max_len (128 on llama-tiny): the
    call degrades to the plain path with identical output."""
    prompt = list(range(1, 100))
    ref = tiny_server.generate(prompt, max_new_tokens=28)
    out = tiny_server.generate_speculative(prompt, max_new_tokens=28, k=8)
    np.testing.assert_array_equal(out, ref)


def test_speculative_rejects_single_row_batches(tiny_server):
    with pytest.raises(ValueError, match="single-row"):
        tiny_server.generate_speculative([[1, 2], [3, 4]],
                                         max_new_tokens=4)


def test_handler_speculative_knob(tmp_path):
    """`"speculative": k` on /invoke routes through speculative decoding:
    same tokens as the plain request, plus acceptance counters; invalid
    combinations get clean API errors."""
    from tests.test_runtime import make_model_bundle
    from lambdipy_tpu.runtime.loader import load_bundle

    bundle = make_model_bundle(
        tmp_path, model="llama-tiny",
        handler="lambdipy_tpu.runtime.handlers:generate_handler",
        extra={"max_new_tokens": "16"})
    report = load_bundle(bundle, warmup=False)
    plain = report.handler.invoke(report.state, {"tokens": [5, 6, 7, 8]})
    spec = report.handler.invoke(report.state,
                                 {"tokens": [5, 6, 7, 8],
                                  "speculative": 4})
    assert spec["ok"], spec
    assert spec["tokens"] == plain["tokens"]
    assert spec["speculative"]["emitted"] >= 16
    sampled = report.handler.invoke(report.state,
                                    {"tokens": [1, 2], "speculative": 4,
                                     "temperature": 0.7, "seed": 5})
    again = report.handler.invoke(report.state,
                                  {"tokens": [1, 2], "speculative": 4,
                                   "temperature": 0.7, "seed": 5})
    assert sampled["ok"] and sampled["tokens"] == again["tokens"]
    assert sampled["speculative"]["steps"] >= 1
    bad2 = report.handler.invoke(report.state,
                                 {"tokens": [[1, 2], [3, 4]],
                                  "speculative": 4})
    assert not bad2["ok"]


def test_speculative_stats_fallback_and_stream_compose(tmp_path):
    """The fallback path returns its own stats (never another request's),
    and stream + speculative composes (VERDICT r5 weak #2): chunks are
    per-verify-step accepted prefixes whose concatenation equals the
    non-streamed speculative output, with the acceptance counters on
    the final record."""
    from tests.test_runtime import make_model_bundle
    from lambdipy_tpu.runtime.loader import load_bundle

    bundle = make_model_bundle(
        tmp_path, model="llama-tiny",
        handler="lambdipy_tpu.runtime.handlers:generate_handler",
        extra={"max_new_tokens": "8"})
    report = load_bundle(bundle, warmup=False)
    # llama-tiny max_len=128: prompt 115 + 8 new + kb 8 > 128 -> fallback
    long = report.handler.invoke(report.state,
                                 {"tokens": list(range(1, 116)),
                                  "speculative": 8, "max_new_tokens": 8})
    assert long["ok"], long
    assert long["speculative"].get("fallback") == "plain", long["speculative"]
    fused = report.handler.invoke(
        report.state, {"tokens": [5, 6, 7, 8], "speculative": 4,
                       "max_new_tokens": 16})
    chunks = list(report.state.invoke_stream(
        {"tokens": [5, 6, 7, 8], "speculative": 4, "stream": True,
         "max_new_tokens": 16}))
    assert all(c["ok"] for c in chunks), chunks
    streamed = [t for c in chunks if c.get("tokens")
                for t in c["tokens"][0]]
    assert streamed == fused["tokens"][0]
    final = chunks[-1]
    assert final.get("done") and final["speculative"]["steps"] >= 1
    # per-step chunks: with acceptance happening, fewer chunks than
    # tokens proves multi-token segments flowed
    assert len(chunks) - 1 <= final["speculative"]["steps"]


def test_speculative_bypasses_continuous_batcher(tmp_path):
    """On a batch_mode='continuous' bundle a speculative request is
    served solo through the spec path (never enqueued into the engine)
    and still matches the engine-served plain output."""
    from tests.test_runtime import make_model_bundle
    from lambdipy_tpu.runtime.loader import load_bundle

    bundle = make_model_bundle(
        tmp_path, model="llama-tiny",
        handler="lambdipy_tpu.runtime.handlers:generate_handler",
        extra={"max_new_tokens": "8", "batch_mode": "continuous",
               "batch_max": "2", "batch_segment": "4"})
    report = load_bundle(bundle, warmup=False)
    plain = report.handler.invoke(report.state, {"tokens": [5, 6, 7]})
    spec = report.handler.invoke(report.state,
                                 {"tokens": [5, 6, 7], "speculative": 4})
    assert spec["ok"] and spec["tokens"] == plain["tokens"]
    engine = report.state.stats()["batching"]
    # exactly the ONE plain request rode the engine — a speculative
    # request enqueued into it would make this 2
    assert engine["requests_served"] == 1, engine
    assert "speculative" in spec


def test_speculative_stream_matches_fused(tiny_server):
    """Server-level parity: generate_speculative_stream chunk concat ==
    generate_speculative output (including through an eos latch), with
    logprobs riding and stats_out filled per request."""
    import numpy as np

    fused, stats = tiny_server.generate_speculative(
        [5, 6, 7, 8], max_new_tokens=16, k=4, return_stats=True)
    out_stats = {}
    chunks = list(tiny_server.generate_speculative_stream(
        [5, 6, 7, 8], max_new_tokens=16, k=4, stats_out=out_stats))
    st = np.concatenate(chunks, axis=1)
    np.testing.assert_array_equal(st, fused[:, :st.shape[1]])
    assert st.shape[1] == 16
    assert out_stats["steps"] == stats["steps"], (out_stats, stats)
    # logprobs parity
    ft, fl = tiny_server.generate_speculative(
        [1, 2, 3], max_new_tokens=12, k=4, return_logprobs=True)
    pairs = list(tiny_server.generate_speculative_stream(
        [1, 2, 3], max_new_tokens=12, k=4, return_logprobs=True))
    st = np.concatenate([p[0] for p in pairs], axis=1)
    sl = np.concatenate([p[1] for p in pairs], axis=1)
    np.testing.assert_array_equal(st, ft[:, :st.shape[1]])
    np.testing.assert_allclose(sl, fl[:, :sl.shape[1]], rtol=1e-5,
                               atol=1e-6)
    # eos: stream stops at the latch; fused pads with filler after it
    free = tiny_server.generate_speculative([5, 6, 7, 8],
                                            max_new_tokens=10)
    eos = int(free[0, 2])
    ref = tiny_server.generate_speculative([5, 6, 7, 8],
                                           max_new_tokens=10, eos_id=eos)
    got = np.concatenate(list(tiny_server.generate_speculative_stream(
        [5, 6, 7, 8], max_new_tokens=10, k=4, eos_id=eos)), axis=1)
    np.testing.assert_array_equal(got, ref[:, :got.shape[1]])
    assert got[0, -1] == eos


def test_speculative_composes_with_prefix(tiny_server):
    """Speculative decoding from a cached prefix KV (system prompt +
    greedy speculation): only the suffix prefills, the prefix tokens
    still feed the lookup drafts, and the output is bitwise the
    full-prompt speculative (== plain greedy) output, fused and
    streamed, with logprobs riding."""
    prefix, suffix = list(range(1, 20)), [4, 5]
    full = tiny_server.generate_speculative(prefix + suffix,
                                            max_new_tokens=16, k=4)
    via, stats = tiny_server.generate_speculative(
        suffix, max_new_tokens=16, k=4, prefix=prefix, return_stats=True)
    np.testing.assert_array_equal(via, full)
    np.testing.assert_array_equal(
        via, tiny_server.generate(prefix + suffix, max_new_tokens=16))
    assert stats["steps"] >= 1
    st = np.concatenate(list(tiny_server.generate_speculative_stream(
        suffix, max_new_tokens=16, k=4, prefix=prefix)), axis=1)
    np.testing.assert_array_equal(st, full[:, : st.shape[1]])
    ft, fl = tiny_server.generate_speculative(
        suffix, max_new_tokens=12, k=4, prefix=prefix,
        return_logprobs=True)
    rt, rl = tiny_server.generate_speculative(
        prefix + suffix, max_new_tokens=12, k=4, return_logprobs=True)
    np.testing.assert_array_equal(ft, rt)
    np.testing.assert_allclose(fl, rl, rtol=1e-4, atol=1e-4)


def test_handler_speculative_with_prefix(tmp_path):
    """`"speculative": k` + `"prefix": [...]` through /invoke and the
    stream path: tokens match the concatenated-prompt speculative
    request, with prefix_cached and the counters on the response."""
    from tests.test_runtime import make_model_bundle
    from lambdipy_tpu.runtime.loader import load_bundle

    bundle = make_model_bundle(
        tmp_path, model="llama-tiny",
        handler="lambdipy_tpu.runtime.handlers:generate_handler",
        extra={"max_new_tokens": "12"})
    report = load_bundle(bundle, warmup=False)
    full = report.handler.invoke(
        report.state, {"tokens": list(range(1, 20)) + [4, 5],
                       "speculative": 4})
    via = report.handler.invoke(
        report.state, {"tokens": [4, 5], "prefix": list(range(1, 20)),
                       "speculative": 4})
    assert via["ok"], via
    assert via["tokens"] == full["tokens"]
    assert via["prefix_cached"] and via["speculative"]["steps"] >= 1
    chunks = list(report.state.invoke_stream(
        {"tokens": [4, 5], "prefix": list(range(1, 20)),
         "speculative": 4, "stream": True}))
    streamed = [t for c in chunks if c.get("tokens")
                for t in c["tokens"][0]]
    assert streamed == full["tokens"][0][:len(streamed)]
    assert chunks[-1].get("prefix_cached")


def test_spec_accept_resample_is_exactly_target_distributed():
    """The delta-proposal rejection core's identity, checked empirically:
    over many keys, the first emitted token's distribution equals the
    target row distribution (accept d0 w.p. p0(d0), else resample from
    the residual)."""
    import jax
    import jax.numpy as jnp

    from lambdipy_tpu.models.llama import _spec_accept_resample

    rng = np.random.default_rng(0)
    v, kb = 8, 4
    logits = rng.standard_normal((kb, v)) * 1.5
    probs = jnp.asarray(
        np.exp(logits) / np.exp(logits).sum(-1, keepdims=True),
        jnp.float32)
    draft = jnp.asarray([2, 5, 1], jnp.int32)
    n = 40000
    keys = jax.vmap(
        lambda i: jax.random.split(jax.random.PRNGKey(i), kb))(
        jnp.arange(n))
    m_all, new_all = jax.vmap(
        lambda ks: _spec_accept_resample(probs, draft, ks))(keys)
    first = np.where(np.asarray(m_all) >= 1, int(draft[0]),
                     np.asarray(new_all))
    emp = np.bincount(first, minlength=v) / n
    assert np.abs(emp - np.asarray(probs[0])).max() < 0.015


def test_sampled_speculative_deterministic_and_composes(tiny_server):
    """temperature > 0 speculation: seed-deterministic, varies across
    seeds, respects top-k masking, streams with fused parity, and the
    compiled ('spec_s', ...) program is reused across requests."""
    a = tiny_server.generate_speculative([5, 6, 7], max_new_tokens=10,
                                         k=4, temperature=1.2, seed=42)
    b = tiny_server.generate_speculative([5, 6, 7], max_new_tokens=10,
                                         k=4, temperature=1.2, seed=42)
    np.testing.assert_array_equal(a, b)
    draws = [tiny_server.generate_speculative(
        [5, 6, 7], max_new_tokens=10, k=4, temperature=1.2, seed=s)
        for s in range(6)]
    assert any(not np.array_equal(draws[0], d) for d in draws[1:])
    # top_k=1 collapses sampled speculation to greedy speculation
    g = tiny_server.generate_speculative([5, 6, 7], max_new_tokens=10,
                                         k=4)
    t1 = tiny_server.generate_speculative([5, 6, 7], max_new_tokens=10,
                                          k=4, temperature=2.0, top_k=1,
                                          seed=9)
    np.testing.assert_array_equal(g, t1)
    # streamed sampled spec == fused sampled spec (same seed)
    st = np.concatenate(list(tiny_server.generate_speculative_stream(
        [5, 6, 7], max_new_tokens=10, k=4, temperature=1.2, seed=42)),
        axis=1)
    np.testing.assert_array_equal(st, a[:, : st.shape[1]])
    # compile-once: a second sampled request adds no program
    count = tiny_server.compile_count
    tiny_server.generate_speculative([9, 8], max_new_tokens=6, k=4,
                                     temperature=0.7, top_p=0.9, seed=3)
    assert tiny_server.compile_count == count


@pytest.mark.slow  # fresh model + three compiles on the 1-core box
def test_speculative_under_int8_kv_cache():
    """Speculation composes with kv_quant='int8': the verify chunk
    attends the quantized cache through the same scalar-index branch,
    and greedy parity with the plain int8-KV decode holds (both paths
    read identically quantized K/V)."""
    import dataclasses

    import jax
    import jax.numpy as jnp

    from lambdipy_tpu.models.llama import (LLAMA_TINY, LlamaModel,
                                           LlamaServer)

    cfg = dataclasses.replace(LLAMA_TINY, kv_quant="int8")
    module = LlamaModel(cfg)
    tokens = jnp.asarray([[5, 6, 7, 8]], jnp.int32)
    params = module.init(jax.random.PRNGKey(0), tokens)
    server = LlamaServer(module, params)
    ref = server.generate([5, 6, 7, 8], max_new_tokens=16)
    for k in (2, 4):
        out = server.generate_speculative([5, 6, 7, 8],
                                          max_new_tokens=16, k=k)
        np.testing.assert_array_equal(out, ref, err_msg=f"k={k}")
