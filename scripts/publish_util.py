"""Shared BASELINE.json publisher for the measurement scripts.

One writer implementation, two invariants (both learned the hard way in
round 5's measurement suite):

- **merge, never replace**: ``published.config5`` accumulates dict-valued
  sub-records from independent modes (``speculative`` / ``concurrent`` /
  ``kv_int8`` / ``prefill`` / ``cold_start_stages``); a config-level
  refresh must not wipe the sub-records other modes published.
- **atomic write**: the suite runs every mode under ``timeout``; a
  SIGTERM landing mid-write must not leave BASELINE.json truncated for
  every later mode to crash on.
"""

from __future__ import annotations

import json
import os
from pathlib import Path

REPO = Path(__file__).resolve().parents[1]

# The micro-exemplar / real-8B disambiguation sentinels, defined ONCE:
# both writers (measure_8b, measure_baseline) and the router below key
# on these. The 8B check is a prefix match because historical records
# carry suffixes (e.g. ", scripts/measure_8b.py").
MICRO_RECIPE = "jax-llama-micro"
RECIPE_8B = "jax-llama3-8b (tp=1 single-chip measurement)"


def is_8b_record(rec: dict) -> bool:
    return str(rec.get("recipe", "")).startswith("jax-llama3-8b")


def write_doc(doc: dict, path: Path | None = None) -> Path:
    """Atomically write the BASELINE.json document."""
    path = path or REPO / "BASELINE.json"
    tmp = path.with_suffix(".json.tmp")
    try:
        tmp.write_text(json.dumps(doc, indent=2))
        os.replace(tmp, path)
    finally:
        tmp.unlink(missing_ok=True)
    return path


def merge_publish(records: dict, path: Path | None = None) -> Path:
    """Merge per-config measurement records into ``published``.

    Each config merges key-by-key into the existing record, so
    dict-valued sub-records the update does not carry survive. A
    ``config5`` record for the micro exemplar arriving while ``config5``
    holds the real-8B decode record is routed to ``config5_micro``
    instead of mislabeling 8B sub-records as micro numbers.
    """
    path = path or REPO / "BASELINE.json"
    doc = json.loads(path.read_text())
    pub = doc.setdefault("published", {})
    for key, rec in records.items():
        if (key == "config5" and isinstance(rec, dict)
                and rec.get("recipe") == MICRO_RECIPE
                and is_8b_record(pub.get("config5", {}))):
            key = "config5_micro"
        cur = pub.get(key)
        if isinstance(cur, dict) and isinstance(rec, dict):
            _deep_update(cur, rec)
        else:
            pub[key] = rec
    return write_doc(doc, path)


def _deep_update(cur: dict, rec: dict) -> None:
    """Recursive merge: updating a config with a partial sub-record
    (e.g. attaching a methodology_note to ``kv_int8``) must not replace
    the sub-record wholesale — a one-level update did exactly that and
    silently dropped a published error-bound."""
    for k, v in rec.items():
        if isinstance(cur.get(k), dict) and isinstance(v, dict):
            _deep_update(cur[k], v)
        else:
            cur[k] = v
