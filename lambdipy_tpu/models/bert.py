"""BERT-base encoder + classification head in flax.linen.

BASELINE.json config 4's model family, implemented TPU-native (the
torch-xla variant is the compatibility path; this is the serving path).
bf16 matmuls, fp32 layernorm/softmax accumulations, static max_len so XLA
compiles one shape.
"""

from __future__ import annotations

import dataclasses

import jax.numpy as jnp
from flax import linen as nn


@dataclasses.dataclass(frozen=True)
class BertConfig:
    vocab_size: int = 30522
    hidden: int = 768
    layers: int = 12
    heads: int = 12
    mlp: int = 3072
    max_len: int = 512
    type_vocab: int = 2
    num_classes: int = 2
    dtype: jnp.dtype = jnp.bfloat16


BERT_BASE = BertConfig()
BERT_TINY = BertConfig(vocab_size=1024, hidden=32, layers=2, heads=2,
                       mlp=64, max_len=64, num_classes=2)


class SelfAttention(nn.Module):
    cfg: BertConfig

    @nn.compact
    def __call__(self, x, mask):
        cfg = self.cfg
        head_dim = cfg.hidden // cfg.heads
        dense = lambda name: nn.DenseGeneral(  # noqa: E731
            (cfg.heads, head_dim), axis=-1, dtype=cfg.dtype, name=name)
        q = dense("query")(x)
        k = dense("key")(x)
        v = dense("value")(x)
        # fp32 softmax accumulation; bf16 matmuls feed the MXU
        logits = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32)
        logits = logits / jnp.sqrt(head_dim).astype(jnp.float32)
        logits = jnp.where(mask[:, None, None, :], logits, jnp.float32(-1e9))
        probs = nn.softmax(logits, axis=-1).astype(cfg.dtype)
        out = jnp.einsum("bhqk,bkhd->bqhd", probs, v)
        return nn.DenseGeneral(cfg.hidden, axis=(-2, -1), dtype=cfg.dtype,
                               name="out")(out)


class EncoderLayer(nn.Module):
    cfg: BertConfig

    @nn.compact
    def __call__(self, x, mask):
        cfg = self.cfg
        ln = lambda name: nn.LayerNorm(epsilon=1e-12, dtype=jnp.float32, name=name)  # noqa: E731
        y = SelfAttention(cfg, name="attn")(x, mask)
        x = ln("ln_attn")(x + y).astype(cfg.dtype)
        y = nn.Dense(cfg.mlp, dtype=cfg.dtype, name="mlp_in")(x)
        # exact (erf) gelu, matching the BERT paper / HF checkpoints so
        # imported weights reproduce reference logits (convert.py)
        y = nn.gelu(y, approximate=False)
        y = nn.Dense(cfg.hidden, dtype=cfg.dtype, name="mlp_out")(y)
        return ln("ln_mlp")(x + y).astype(cfg.dtype)


class BertEncoder(nn.Module):
    cfg: BertConfig

    @nn.compact
    def __call__(self, input_ids, attention_mask=None, token_type_ids=None):
        cfg = self.cfg
        b, s = input_ids.shape
        if attention_mask is None:
            attention_mask = jnp.ones((b, s), dtype=jnp.bool_)
        else:
            attention_mask = attention_mask.astype(jnp.bool_)
        if token_type_ids is None:
            token_type_ids = jnp.zeros((b, s), dtype=jnp.int32)
        emb = nn.Embed(cfg.vocab_size, cfg.hidden, dtype=cfg.dtype,
                       name="tok_emb")(input_ids)
        emb += nn.Embed(cfg.max_len, cfg.hidden, dtype=cfg.dtype,
                        name="pos_emb")(jnp.arange(s)[None, :])
        emb += nn.Embed(cfg.type_vocab, cfg.hidden, dtype=cfg.dtype,
                        name="type_emb")(token_type_ids)
        x = nn.LayerNorm(epsilon=1e-12, dtype=jnp.float32, name="emb_ln")(emb)
        x = x.astype(cfg.dtype)
        for i in range(cfg.layers):
            x = EncoderLayer(cfg, name=f"layer_{i}")(x, attention_mask)
        return x


class BertClassifier(nn.Module):
    cfg: BertConfig

    @nn.compact
    def __call__(self, input_ids, attention_mask=None, token_type_ids=None):
        x = BertEncoder(self.cfg, name="encoder")(
            input_ids, attention_mask, token_type_ids)
        cls = x[:, 0]  # [CLS] pooling
        pooled = jnp.tanh(nn.Dense(self.cfg.hidden, dtype=self.cfg.dtype,
                                   name="pooler")(cls))
        return nn.Dense(self.cfg.num_classes, dtype=jnp.float32,
                        name="classifier")(pooled)
