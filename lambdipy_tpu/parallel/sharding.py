"""Sharding rules: parameter-path patterns -> PartitionSpec.

Models stay sharding-agnostic (plain flax modules); the mapping from
parameter paths to mesh axes lives here, so the same model runs single-chip
(all specs replicated), TP-served on v5e-4, or FSDP-trained, by swapping
rule sets. XLA inserts the collectives implied by the shardings (the
scaling-book recipe: pick a mesh, annotate, let XLA place all-gathers /
reduce-scatters on ICI).
"""

from __future__ import annotations

import contextlib
import contextvars
import fnmatch
from dataclasses import dataclass

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


@dataclass(frozen=True)
class ShardingRules:
    """Ordered (path-glob, PartitionSpec) rules; first match wins.

    Paths are '/'-joined pytree key paths, e.g.
    ``params/layers_0/attn/q_proj/kernel``.
    """

    rules: tuple[tuple[str, P], ...]
    default: P = P()

    def spec_for(self, path: str) -> P:
        for pattern, spec in self.rules:
            if fnmatch.fnmatch(path, pattern):
                return spec
        return self.default


def _path_str(key_path) -> str:
    parts = []
    for k in key_path:
        if hasattr(k, "key"):
            parts.append(str(k.key))
        elif hasattr(k, "idx"):
            parts.append(str(k.idx))
        else:
            parts.append(str(k))
    return "/".join(parts)


def _filter_spec(spec: P, mesh: Mesh, ndim: int) -> P:
    """Drop axes not present in the mesh (size-1 axes are omitted from Mesh
    by make_mesh) and truncate/pad to the array rank, so one rule set works
    across mesh shapes."""
    names = set(mesh.axis_names)

    def keep(entry):
        if entry is None:
            return None
        if isinstance(entry, (tuple, list)):
            kept = tuple(e for e in entry if e in names)
            return kept if kept else None
        return entry if entry in names else None

    entries = [keep(e) for e in spec]
    entries = entries[:ndim] + [None] * max(0, ndim - len(entries))
    return P(*entries)


def named_sharding(mesh: Mesh, *entries) -> NamedSharding:
    return NamedSharding(mesh, _filter_spec(P(*entries), mesh, len(entries)))


def shard_params(params, mesh: Mesh, rules: ShardingRules):
    """Device-put a parameter pytree according to path rules."""

    def place(key_path, leaf):
        spec = _filter_spec(rules.spec_for(_path_str(key_path)), mesh, leaf.ndim)
        return jax.device_put(leaf, NamedSharding(mesh, spec))

    return jax.tree_util.tree_map_with_path(place, params)


def param_shardings(params, mesh: Mesh, rules: ShardingRules):
    """The NamedSharding pytree for ``params`` (for jit in_shardings)."""

    def spec(key_path, leaf):
        return NamedSharding(
            mesh, _filter_spec(rules.spec_for(_path_str(key_path)), mesh, leaf.ndim))

    return jax.tree_util.tree_map_with_path(spec, params)


_HINTS_DISABLED: contextvars.ContextVar[bool] = contextvars.ContextVar(
    "lambdipy_shard_hints_disabled", default=False)


@contextlib.contextmanager
def no_shard_hints():
    """Disable :func:`shard_hint` while tracing manual (shard_map) bodies,
    where whole-mesh sharding constraints are invalid — the per-device code
    there already owns its layout."""
    token = _HINTS_DISABLED.set(True)
    try:
        yield
    finally:
        _HINTS_DISABLED.reset(token)


def shard_hint(x, *entries):
    """Best-effort ``with_sharding_constraint`` against the ambient mesh.

    Entries are mesh axis names (or None) per array dim, truncated/padded to
    the rank. Axes absent from the ambient mesh — or larger than the dim
    they would split — are dropped, so models stay mesh-agnostic: the same
    call is a no-op single-chip, pins tp/sp layouts when those axes exist,
    and is suppressed inside shard_map regions (:func:`no_shard_hints`).
    """
    if _HINTS_DISABLED.get():
        return x
    from lambdipy_tpu.parallel.mesh import current_mesh

    mesh = current_mesh()
    if mesh is None:
        return x
    spec = _filter_spec(P(*entries), mesh, x.ndim)
    kept = []
    for i, e in enumerate(spec):
        size = 1
        for a in (e if isinstance(e, tuple) else (e,)) if e else ():
            size *= mesh.shape[a]
        kept.append(e if size <= x.shape[i] else None)
    if all(e is None for e in kept):
        # no requested axis exists on this mesh — leave the layout to the
        # partitioner rather than forcing replication
        return x
    return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, P(*kept)))


def shard_hints_suppressed() -> bool:
    """True while tracing a manual (shard_map) region — whole-mesh
    constraints and nested whole-mesh shard_maps are both invalid there."""
    return _HINTS_DISABLED.get()


def device_bytes(tree) -> tuple[int, int]:
    """``(per_device_max, logical_total)`` bytes of a pytree of jax
    arrays: ``logical_total`` is the unsharded footprint (sum of
    ``nbytes``); ``per_device_max`` sums each leaf's addressable shard
    bytes per device and takes the busiest device — the number HBM
    capacity planning actually cares about. A replicated leaf costs its
    full ``nbytes`` on every device; a tp-sharded one 1/tp. Host-only
    metadata reads — never touches device data."""
    import jax

    per_dev: dict = {}
    total = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        nbytes = getattr(leaf, "nbytes", None)
        if nbytes is None:
            continue
        total += int(nbytes)
        shards = getattr(leaf, "addressable_shards", None)
        if not shards:
            per_dev[None] = per_dev.get(None, 0) + int(nbytes)
            continue
        for sh in shards:
            key = getattr(sh.device, "id", sh.device)
            per_dev[key] = per_dev.get(key, 0) + int(sh.data.nbytes)
    return (max(per_dev.values()) if per_dev else 0), total


def shard_batch(batch, mesh: Mesh, axis: str = "dp"):
    """Shard the leading (batch) dim of every leaf over the data axes."""

    def place(leaf):
        spec = _filter_spec(P(axis), mesh, leaf.ndim)
        return jax.device_put(leaf, NamedSharding(mesh, spec))

    return jax.tree_util.tree_map(place, batch)
