"""Timing helpers.

The serve path's cold-start budget (<10 s, BASELINE.md) is consumed almost
entirely by interpreter + PJRT init + first compile, so every stage of boot
and build is timed with :class:`StageTimer` and reported in structured logs.
Mirrors the per-stage timing the build engine needs (SURVEY.md §6 tracing
row: the reference has none; the rebuild makes it first-class).
"""

from __future__ import annotations

import time
from contextlib import contextmanager
from dataclasses import dataclass, field


@dataclass
class Timer:
    """Monotonic stopwatch."""

    start: float = field(default_factory=time.monotonic)

    def elapsed(self) -> float:
        return time.monotonic() - self.start

    def lap(self) -> float:
        now = time.monotonic()
        out = now - self.start
        self.start = now
        return out


@dataclass
class StageTimer:
    """Accumulates named stage durations; used for cold-start breakdowns."""

    stages: dict[str, float] = field(default_factory=dict)

    @contextmanager
    def stage(self, name: str):
        t0 = time.monotonic()
        try:
            yield
        finally:
            self.stages[name] = self.stages.get(name, 0.0) + (time.monotonic() - t0)

    def total(self) -> float:
        return sum(self.stages.values())

    def report(self) -> dict[str, float]:
        out = {k: round(v, 4) for k, v in self.stages.items()}
        out["total"] = round(self.total(), 4)
        return out
