"""Whole-prompt sequence-parallel prefill (models/llama.py sp_prefill
family + parallel/ring.py sp_chunk_attention).

The bar is the serving standard everywhere both paths exist: the
sharded program's OUTPUT must match the serial chain's (allclose at the
attention level, token-for-token through the runners), ragged last
rounds pad without contaminating reachable cells, paged scatter lands in
shuffled tables without touching distractor pages, and asking for sp
without a mesh stands down counted — never silently."""

import jax.numpy as jnp
import numpy as np
import pytest

from lambdipy_tpu.models import registry
from lambdipy_tpu.models.llama import (
    _attend,
    _continue_prefill,
    _serve_select,
    resolve_sp_prefill,
)
from lambdipy_tpu.parallel.mesh import make_mesh, use_mesh
from lambdipy_tpu.parallel.ring import sp_chunk_attention
from lambdipy_tpu.parallel.sharding import shard_params


def _rand(shape, seed):
    return jnp.asarray(np.random.default_rng(seed).normal(size=shape),
                       jnp.float32)


# -- the sharded-vs-dense attention oracle -----------------------------------


@pytest.mark.parametrize("kvh", [4, 2])
def test_sp_chunk_attention_matches_dense(cpu_devices, kvh):
    """Query-sharded chunk attention over a replicated cache == the
    dense reference, GQA included, under an arbitrary validity mask."""
    b, s, t, h, d = 2, 32, 48, 4, 16
    q = _rand((b, s, h, d), 0)
    k = _rand((b, t, kvh, d), 1)
    v = _rand((b, t, kvh, d), 2)
    # the serve-path mask shape [b, s, t]: causal from a cache index,
    # i.e. query j attends keys <= idx + j
    idx = 16
    mask = (jnp.arange(t)[None, None, :]
            <= (idx + jnp.arange(s))[None, :, None])
    mask = jnp.broadcast_to(mask, (b, s, t))
    ref = _attend(q, k, v, mask)
    mesh = make_mesh({"sp": 4}, devices=cpu_devices[:4])
    out = sp_chunk_attention(q, k, v, mask, mesh)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(out),
                               rtol=2e-4, atol=2e-4)


def test_sp_chunk_attention_banded_mask(cpu_devices):
    """The long-context sliding band (keys >= band_start per query) is
    just another mask to the sharded kernel — parity must hold when
    rows attend DIFFERENT key windows across shards."""
    b, s, t, h, d = 1, 32, 64, 2, 8
    q = _rand((b, s, h, d), 3)
    k = _rand((b, t, h, d), 4)
    v = _rand((b, t, h, d), 5)
    idx, band = 16, 16
    qpos = idx + jnp.arange(s)
    valid = (jnp.arange(t)[None, None, :] <= qpos[None, :, None])
    band_start = jnp.maximum(0, (qpos // band - 1) * band)
    valid = valid & (jnp.arange(t)[None, None, :]
                     >= band_start[None, :, None])
    mask = jnp.broadcast_to(valid, (b, s, t))
    ref = _attend(q, k, v, mask)
    mesh = make_mesh({"sp": 2}, devices=cpu_devices[:2])
    out = sp_chunk_attention(q, k, v, mask, mesh)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(out),
                               rtol=2e-4, atol=2e-4)


def test_sp_chunk_attention_rejects_uneven_width(cpu_devices):
    b, s, t, h, d = 1, 30, 32, 2, 8
    q, k, v = (_rand((b, n, h, d), i) for i, n in [(0, s), (1, t), (2, t)])
    mask = jnp.ones((b, s, t), jnp.bool_)
    mesh = make_mesh({"sp": 4}, devices=cpu_devices[:4])
    with pytest.raises(ValueError, match="not divisible"):
        sp_chunk_attention(q, k, v, mask, mesh)


# -- program-family parity on the serving stack ------------------------------


@pytest.fixture(scope="module")
def sp_server(cpu_devices):
    """A tiny server on an sp=2 mesh: the sp-prefill programs shard
    over it, the serial programs ignore it — one server serves as both
    sides of every parity check below."""
    adapter = registry.get("llama-tiny").build()
    params = adapter.init_params(seed=0)
    mesh = make_mesh({"sp": 2}, devices=cpu_devices[:2])
    with use_mesh(mesh):
        sp_params = shard_params(params, mesh, adapter.tp_rules)
    return adapter.make_server(sp_params, mesh=mesh, prefill_chunk=16)


def _cache_kv(cache, upto):
    """Concatenate the reachable K/V cells of a serve cache."""
    out = []
    for entry in cache:
        for name in ("k", "v"):
            out.append(np.asarray(entry[name])[:, :upto])
    return out


def test_sp_prefill_cache_matches_chunked_walk(sp_server):
    """The whole-prompt sp walk must land the same cache the serial
    chunk chain lands — including a RAGGED last round (upto chosen so
    the final round pads) and rounds at several shard bases."""
    server = sp_server
    cfg = server.model.cfg
    rng = np.random.default_rng(7)
    # 3 sp rounds of 2 chunks each, last one ragged
    ck = server.prefill_chunk
    upto = 2 * (2 * ck) + ck + 3
    assert upto < cfg.max_len
    row = rng.integers(5, cfg.vocab_size - 5, size=upto).tolist()
    with server._mesh_ctx():
        serial = server._chunked_prefill_cache(row, upto, cfg.max_len)
        sharded = server._chunked_prefill_cache(row, upto, cfg.max_len,
                                                sp=2)
    assert int(np.asarray(serial[0]["index"])) == upto
    assert int(np.asarray(sharded[0]["index"])) == upto
    for a, b in zip(_cache_kv(serial, upto), _cache_kv(sharded, upto)):
        np.testing.assert_allclose(a, b, rtol=5e-4, atol=5e-4)


def test_sp_continue_prefill_pos_offset_parity(sp_server):
    """``pos_offset`` (the long-context logical-position split) must
    reach RoPE identically under the sharded program at EVERY shard
    base: serial vs sp ``_continue_prefill`` on the same cache,
    swept over offsets."""
    server = sp_server
    cfg = server.model.cfg
    rng = np.random.default_rng(11)
    base, sbs = 32, 32
    row = rng.integers(5, cfg.vocab_size - 5, size=base + sbs).tolist()
    t_op, k_op, p_op, keys0, eos_op = server._knob_operands(
        0.0, None, None, 0, None, b=1)
    select = _serve_select(t_op, k_op, p_op)
    for off in (0, 16, 48):
        with server._mesh_ctx():
            pf = server._prefix_first_fn(base, cfg.max_len)
            prompt_op, _ = server._pad_rows([row[:base]], [base], 1, base)
            suffix_op, _ = server._pad_rows([row[base:]], [sbs], 1, sbs)
            outs = []
            for sp in (0, 2):
                cache = pf(server.params, prompt_op, jnp.int32(base))
                outs.append(_continue_prefill(
                    server.model, server.params, cache, suffix_op,
                    jnp.int32(sbs), select, keys0,
                    eos_op, sbs, pos_offset=jnp.int32(off),
                    sp_prefill=sp))
        (f0, lp0s, c0, s0, _, _), (f1, lp1s, c1, s1, _, _) = outs
        assert int(np.asarray(f0[0])) == int(np.asarray(f1[0])), \
            f"first token diverged at pos_offset={off}"
        np.testing.assert_allclose(np.asarray(lp0s), np.asarray(lp1s),
                                   rtol=5e-4, atol=5e-4)
        assert np.array_equal(np.asarray(s0), np.asarray(s1))
        for a, b in zip(_cache_kv(c0, base + sbs),
                        _cache_kv(c1, base + sbs)):
            np.testing.assert_allclose(a, b, rtol=5e-4, atol=5e-4)


def test_lsp_round_scatters_into_shuffled_pages(sp_server):
    """The paged sp round writes each shard's KV through the arena
    page tables: a SHUFFLED (non-contiguous) table must land the same
    bytes a fresh dense prefill computes, distractor pages holding
    garbage must come back bitwise untouched, and the null fill slots
    of round 0 must leave the null page bitwise unchanged."""
    from lambdipy_tpu.models.llama import (
        arena_page_slices,
        init_page_arena,
    )
    from lambdipy_tpu.runtime.pagepool import NULL_PAGE

    server = sp_server
    cfg = server.model.cfg
    page, window, sp = 16, 32, 2
    rbs = sp * (window // 2)   # 32-token round, 2 pages
    n_pages = 8
    rng = np.random.default_rng(13)
    row = rng.integers(5, cfg.vocab_size - 5, size=rbs).tolist()

    def _page_bytes(arena, pid):
        return b"".join(np.asarray(x).tobytes()
                        for entry in arena_page_slices(arena, pid, page)
                        for x in entry.values())

    with server._mesh_ctx():
        arena = init_page_arena(cfg, n_pages, page, mesh=server.mesh)
        # salt every page so an accidental write is visible
        write = server._page_write_fn(n_pages, page)
        for pid in range(n_pages):
            salt = [{n: jnp.asarray(rng.normal(size=np.asarray(x).shape),
                                    np.asarray(x).dtype)
                     for n, x in entry.items()}
                    for entry in arena_page_slices(arena, pid, page)]
            arena = write(arena, jnp.int32(pid), salt)
        before = {pid: _page_bytes(arena, pid) for pid in range(n_pages)}
        # shuffled, non-contiguous round pages + the round-0 null fill
        table = [5, 2, NULL_PAGE]
        rnd = server._lsp_round_fn(sp, n_pages, page, window, sp)
        suffix_op, _ = server._pad_rows([row], [rbs], 1, rbs)
        knobs = server._knob_operands(0.0, None, None, 0, None, b=1)
        t_op, k_op, p_op, keys0, eos_op = knobs
        first, lp0, arena, start, done, _ = rnd(
            server.params, arena, jnp.asarray(table, jnp.int32)[None, :],
            jnp.int32(0), jnp.int32(0), suffix_op, jnp.int32(rbs),
            t_op, k_op, p_op, keys0, eos_op)
        # oracle: the same tokens through the dense serve prefill
        ref_cache = server._chunked_prefill_cache(row, rbs, cfg.max_len)
        gather = server._paged_gather_fn(n_pages, page, rbs)
        got = gather(arena, jnp.asarray(table[:2], jnp.int32)[None, :],
                     jnp.int32(rbs))
    assert int(np.asarray(start)[0]) == rbs
    for a, b in zip(_cache_kv(ref_cache, rbs), _cache_kv(got, rbs)):
        np.testing.assert_allclose(a, b, rtol=5e-4, atol=5e-4)
    # distractor pages (garbage) and the null page: bitwise untouched
    for pid in (0, 1, 3, 4, 6, 7):
        assert _page_bytes(arena, pid) == before[pid], \
            f"page {pid} was touched by the sp round scatter"


# -- the long-context runner: sp rounds vs the serial slide chain ------------


@pytest.mark.parametrize("sampled", [False, True])
def test_longctx_sp_rounds_match_serial_chain(sp_server, sampled):
    """ceil(S/(sp*w2)) sharded rounds == the serial window/2 slide
    chain, token for token, greedy AND seeded-sampled, with a ragged
    final round and a multi-slide prompt."""
    from lambdipy_tpu.runtime.longctx import LongContextRunner
    from lambdipy_tpu.runtime.metrics import PrefillStats

    from tests.test_long_context import mk_pool

    server = sp_server
    cfg = server.model.cfg
    rng = np.random.default_rng(17)
    window = 64
    s = 3 * window + window // 2 + 5   # ragged last round at sp=2
    row = rng.integers(5, cfg.vocab_size - 5, size=s).tolist()
    kw = dict(window=window, segment=8, max_logical_ctx=16 * window)
    knobs = (dict(temperature=0.8, top_k=20, seed=5)
             if sampled else dict(temperature=0.0, seed=0))
    serial = LongContextRunner(server, mk_pool(server), **kw).generate(
        row, max_new_tokens=10, **knobs)
    stats = PrefillStats()
    stats.configure("sp", 2)
    pool = mk_pool(server, extra_pages=4)
    runner = LongContextRunner(server, pool, prefill_mode="sp",
                               prefill_stats=stats, **kw)
    sharded = runner.generate(row, max_new_tokens=10, **knobs)
    assert np.array_equal(np.asarray(serial), np.asarray(sharded)), \
        f"sampled={sampled}: sp rounds diverged from the serial chain"
    rep = stats.report()
    assert rep["rounds"] == -(-s // window)  # rbs = sp * w2 = window
    assert rep["sharded_chunks"] > 0
    # every page the runner took went back to the pool
    assert pool.free_count() == pool.capacity_pages


def test_longctx_sp_ragged_tail_releases_pages(sp_server):
    """A ragged FINAL round whose decode view starts exactly at the
    carried history (base == gs) leaves union pages past the view —
    pure padding (tokens >= s). They must go back to the pool, not
    leak: the geometry s = 3*window - window/2 pins off0 == 0 with a
    2-page tail."""
    from lambdipy_tpu.runtime.longctx import LongContextRunner

    from tests.test_long_context import mk_pool

    server = sp_server
    cfg = server.model.cfg
    rng = np.random.default_rng(29)
    window = 64
    s = 3 * window - window // 2   # last round: 32 of 64 tokens real
    row = rng.integers(5, cfg.vocab_size - 5, size=s).tolist()
    kw = dict(window=window, segment=8, max_logical_ctx=8 * window)
    serial = LongContextRunner(server, mk_pool(server), **kw).generate(
        row, max_new_tokens=8, temperature=0.0)
    pool = mk_pool(server, extra_pages=4)
    sharded = LongContextRunner(server, pool, prefill_mode="sp",
                                **kw).generate(
        row, max_new_tokens=8, temperature=0.0)
    assert np.array_equal(np.asarray(serial), np.asarray(sharded))
    assert pool.free_count() == pool.capacity_pages, \
        "ragged-tail union pages leaked from the pool"


def test_longctx_sp_within_window_prompt(sp_server):
    """A prompt over one chunk but under the window: ONE sp round, no
    slide, same tokens as serial — the small-prompt edge of the round
    schedule (and the serial fallback below the gate)."""
    from lambdipy_tpu.runtime.longctx import LongContextRunner

    from tests.test_long_context import mk_pool

    server = sp_server
    cfg = server.model.cfg
    rng = np.random.default_rng(19)
    window = 64
    row = rng.integers(5, cfg.vocab_size - 5, size=window - 7).tolist()
    kw = dict(window=window, segment=8, max_logical_ctx=8 * window)
    serial = LongContextRunner(server, mk_pool(server), **kw).generate(
        row, max_new_tokens=8, temperature=0.0)
    sharded = LongContextRunner(server, mk_pool(server, extra_pages=4),
                                prefill_mode="sp", **kw).generate(
        row, max_new_tokens=8, temperature=0.0)
    assert np.array_equal(np.asarray(serial), np.asarray(sharded))


# -- stand-downs: counted, never silent --------------------------------------


def test_sp_prefill_without_mesh_stands_down():
    from lambdipy_tpu.parallel import spdecode
    from lambdipy_tpu.runtime.continuous import ContinuousBatcher

    spdecode._reset_standdowns_for_tests()
    adapter = registry.get("llama-tiny").build()
    server = adapter.make_server(adapter.init_params(seed=0))
    cb = ContinuousBatcher(server, slots=2, segment=4,
                           prefill_mode="sp")
    assert cb.prefill_sp == 0
    assert cb.prefill_mode == "sp"  # the ask is remembered...
    reasons = spdecode.standdown_stats()["reasons"]
    assert reasons.get("sp_prefill_without_sp_mesh", 0) >= 1
    rep = cb.stats()["prefill"]
    assert rep["mode"] == "sp" and rep["sp"] == 0
    assert rep["standdowns"].get("sp_prefill_without_sp_mesh") == 1


def test_resolve_sp_prefill_modes(cpu_devices):
    assert resolve_sp_prefill("chunked", None) == 0
    mesh = make_mesh({"sp": 2}, devices=cpu_devices[:2])
    assert resolve_sp_prefill("chunked", mesh) == 0
    assert resolve_sp_prefill("sp", mesh) == 2
    tp = make_mesh({"tp": 2}, devices=cpu_devices[:2])
    from lambdipy_tpu.parallel import spdecode

    spdecode._reset_standdowns_for_tests()
    assert resolve_sp_prefill("sp", tp) == 0
    assert spdecode.standdown_stats()["reasons"][
        "sp_prefill_without_sp_mesh"] == 1


def test_engine_sp_prefill_matches_chunked_engine(sp_server):
    """The continuous engine end to end: cold rows prefilled under
    prefill_mode="sp" must emit the same tokens the chunked engine
    emits — group prefill and the long-row chunked joiner both route
    through the sharded programs."""
    from lambdipy_tpu.runtime.continuous import ContinuousBatcher

    server = sp_server
    cfg = server.model.cfg
    rng = np.random.default_rng(23)
    prompts = [rng.integers(5, cfg.vocab_size - 5, size=n).tolist()
               for n in (24, 40, 96)]

    def run(mode):
        from concurrent.futures import ThreadPoolExecutor

        cb = ContinuousBatcher(server, slots=2, segment=8,
                               prefill_mode=mode)
        with ThreadPoolExecutor(max_workers=len(prompts)) as ex:
            futs = [ex.submit(cb.generate, p, max_new_tokens=8,
                              temperature=0.0) for p in prompts]
            return [f.result() for f in futs]

    chunked, sharded = run("chunked"), run("sp")
    for a, b in zip(chunked, sharded):
        assert np.array_equal(np.asarray(a), np.asarray(b))
    # the sp engine actually sharded something, visibly
    cb = ContinuousBatcher(server, slots=2, segment=8,
                           prefill_mode="sp")
    assert cb.prefill_sp == 2
    assert cb.stats()["prefill"]["mode"] == "sp"
