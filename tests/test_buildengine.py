"""Build engine integration tests: vendor + closure + sdist + smoke + bundle
(SURVEY.md §5 plan item 2: hermetic integration against the local stores)."""

import json

import pytest

from lambdipy_tpu.buildengine import build_recipe, import_names, import_smoke
from lambdipy_tpu.buildengine.engine import BuildError
from lambdipy_tpu.buildengine.smoke import SmokeError
from lambdipy_tpu.buildengine.vendor import (
    VendorError,
    dependency_closure,
    find_distribution,
    vendor_distribution,
)
from lambdipy_tpu.bundle import assemble_bundle, load_manifest
from lambdipy_tpu.bundle.format import verify_files
from lambdipy_tpu.recipes.schema import load_recipe_dict


def test_vendor_small_distribution(tmp_path):
    rec = vendor_distribution("click", tmp_path / "site")
    assert rec["name"] == "click" and rec["files"] > 0
    assert (tmp_path / "site" / "click" / "__init__.py").exists()
    versions = import_smoke(tmp_path / "site", ["click"])
    assert "click" in versions


def test_vendor_missing_raises(tmp_path):
    with pytest.raises(VendorError, match="not installed"):
        vendor_distribution("not-a-real-pkg-xyz", tmp_path)


def test_import_names_mapping():
    assert "sklearn" in import_names(find_distribution("scikit-learn"))


def test_dependency_closure_follows_requires():
    closure = dependency_closure(["flax"])
    assert "jax" in closure and "numpy" in closure and "msgpack" in closure


def test_dependency_closure_extras():
    base = dependency_closure(["jax"])
    tpu = dependency_closure(["jax[tpu]"])
    assert "jaxlib" in base
    assert "libtpu" in tpu  # extra-gated dep followed


def test_smoke_fails_on_broken_tree(tmp_path):
    site = tmp_path / "site"
    (site / "brokenpkg").mkdir(parents=True)
    (site / "brokenpkg" / "__init__.py").write_text("import missing_dep_xyz\n")
    with pytest.raises(SmokeError, match="missing_dep_xyz"):
        import_smoke(site, ["brokenpkg"])


def _fake_recipe(**over):
    doc = {
        "schema": 1,
        "name": "clicky",
        "version": "1.0",
        "requires": ["click>=8"],
        "prune": {"rules": ["tests", "pycache", "dist-info-extras"]},
    }
    doc.update(over)
    return load_recipe_dict(doc)


def test_build_vendor_recipe_end_to_end(tmp_path):
    result = build_recipe(_fake_recipe(), tmp_path / "work")
    assert result.smoke_versions.get("click")
    assert result.prune.bytes_after > 0
    prov = result.provenance()
    assert prov["recipe"] == "clicky"
    assert {"stage", "prune", "smoke", "total"} <= set(prov["timings"])


def test_build_missing_required_dist_raises(tmp_path):
    recipe = _fake_recipe(requires=["definitely-not-installed-xyz"])
    with pytest.raises(BuildError, match="not installed"):
        build_recipe(recipe, tmp_path / "work")


def test_build_optional_skip_recorded(tmp_path):
    recipe = _fake_recipe(optional_requires=["definitely-not-installed-xyz"])
    result = build_recipe(recipe, tmp_path / "work")
    assert result.skipped_optional == ["definitely-not-installed-xyz"]


def test_base_layer_subtraction(tmp_path):
    """With numpy in the base layer, a numpy-requiring recipe vendors nothing
    numpy-shaped into the delta."""
    recipe = load_recipe_dict({
        "schema": 1, "name": "thin", "version": "1",
        "requires": ["numpy"], "base_layer": "sci-cpu",
    })
    result = build_recipe(recipe, tmp_path / "work")
    assert not (tmp_path / "work" / "site" / "numpy").exists()
    assert result.smoke_versions.get("numpy")  # still importable via base layer


def test_assemble_bundle_manifest_and_verify(tmp_path):
    result = build_recipe(_fake_recipe(), tmp_path / "work")
    out = tmp_path / "bundle"
    manifest = assemble_bundle(result, out, with_payload=False)
    loaded = load_manifest(out)
    assert loaded["artifact_id"] == manifest["artifact_id"]
    assert loaded["base_layer"]["name"] == "none"
    assert verify_files(out) == []
    # corrupt a file -> verify catches it
    victim = next(f for f in loaded["files"] if f["path"].endswith(".py"))
    (out / victim["path"]).write_text("tampered\n")
    assert any("mismatch" in p for p in verify_files(out))


def test_plain_deps_vendored_at_package_time(tmp_path):
    result = build_recipe(_fake_recipe(), tmp_path / "work")
    out = tmp_path / "bundle"
    assemble_bundle(result, out, plain_deps=["einops"], with_payload=False)
    assert (out / "site" / "einops" / "__init__.py").exists()


@pytest.mark.slow
def test_certifi_sdist_build_end_to_end(tmp_path):
    """The trivial-recipe exemplar: build certifi from its local source
    archive through the sandbox wheel path (SURVEY.md §5 verified exemplar)."""
    from lambdipy_tpu.recipes import builtin_store
    from lambdipy_tpu.resolve.sources import SourceStore

    store = SourceStore(cache=tmp_path / "srccache")
    try:
        store.resolve("certifi")
    except Exception as e:
        pytest.skip(f"certifi source unavailable: {e}")
    recipe = builtin_store().get("certifi")
    result = build_recipe(recipe, tmp_path / "work", sources=store)
    assert (tmp_path / "work" / "site" / "certifi" / "cacert.pem").exists()
    assert result.smoke_versions.get("certifi")
    out = tmp_path / "bundle"
    manifest = assemble_bundle(result, out, with_payload=False)
    assert json.dumps(manifest)  # serializable


# --------------------------------------------------------------------------
# native-compile sdist path (SURVEY.md §9.3: the hard build-from-source leg)


_CEXT_PYPROJECT = """\
[build-system]
requires = ["setuptools>=68"]
build-backend = "setuptools.build_meta"
"""

_CEXT_SETUP = """\
from setuptools import Extension, setup

setup(name="fastsum", version="1.0", packages=["fastsum"],
      ext_modules=[Extension("fastsum._core", ["src/core.c"])])
"""

_CEXT_CORE_C = r"""
#include <Python.h>

static PyObject *checksum(PyObject *self, PyObject *args) {
    Py_buffer buf;
    if (!PyArg_ParseTuple(args, "y*", &buf)) return NULL;
    unsigned long long h = 14695981039346656037ULL; /* FNV-1a 64 basis */
    const unsigned char *p = (const unsigned char *)buf.buf;
    for (Py_ssize_t i = 0; i < buf.len; i++) {
        h ^= p[i];
        h *= 1099511628211ULL;
    }
    PyBuffer_Release(&buf);
    return PyLong_FromUnsignedLongLong(h);
}

static PyMethodDef methods[] = {
    {"checksum", checksum, METH_VARARGS, "FNV-1a 64 over a bytes-like."},
    {NULL, NULL, 0, NULL}};

static struct PyModuleDef mod = {
    PyModuleDef_HEAD_INIT, "_core", NULL, -1, methods};

PyMODINIT_FUNC PyInit__core(void) { return PyModule_Create(&mod); }
"""

_CEXT_INIT = """\
from fastsum._core import checksum

__all__ = ["checksum"]
__version__ = "1.0"
"""


def _cext_source_archive(tmp_path):
    """A /source.tar.gz-shaped archive holding a real C-extension sdist."""
    import io
    import tarfile

    tree = tmp_path / "fastsum-1.0"
    (tree / "src").mkdir(parents=True)
    (tree / "fastsum").mkdir()
    (tree / "pyproject.toml").write_text(_CEXT_PYPROJECT)
    (tree / "setup.py").write_text(_CEXT_SETUP)
    (tree / "src" / "core.c").write_text(_CEXT_CORE_C)
    (tree / "fastsum" / "__init__.py").write_text(_CEXT_INIT)

    inner = io.BytesIO()
    with tarfile.open(fileobj=inner, mode="w:gz") as tar:
        tar.add(tree, arcname="fastsum-1.0")
    outer_path = tmp_path / "source.tar.gz"
    with tarfile.open(outer_path, "w:gz") as tar:
        info = tarfile.TarInfo("Python_fastsum@1.0_source.tar.gz")
        info.size = len(inner.getvalue())
        inner.seek(0)
        tar.addfile(info, inner)
    return outer_path


@pytest.mark.slow
def test_native_cext_sdist_end_to_end(tmp_path):
    """The native-compile leg of the sdist backend, proven with a real C
    extension: source tree -> PEP 517 wheel build (cc compiles core.c) ->
    vendored .so -> guarded ELF strip in the prune pass -> hermetic
    fresh-venv import smoke -> the function actually computes."""
    import subprocess
    import sys

    from lambdipy_tpu.resolve.sources import SourceStore

    store = SourceStore(archive=_cext_source_archive(tmp_path),
                        cache=tmp_path / "cache")
    recipe = load_recipe_dict({
        "schema": 1, "name": "fastsum", "version": "1.0",
        "build": {"backend": "sdist", "source": "fastsum"},
        "prune": {"rules": ["tests", "pycache", "dist-info-extras"]},
    })
    result = build_recipe(recipe, tmp_path / "work", sources=store)

    site = tmp_path / "work" / "site"
    so = list((site / "fastsum").glob("_core*.so"))
    assert so, "compiled extension missing from the vendored site"
    assert result.smoke_versions.get("fastsum") == "1.0"
    # the built artifact really works, from the site tree alone
    out = subprocess.run(
        [sys.executable, "-c",
         "import fastsum; print(fastsum.checksum(b'lambdipy'))"],
        capture_output=True, text=True, env={"PYTHONPATH": str(site),
                                             "PATH": "/usr/bin:/bin"})
    assert out.returncode == 0, out.stderr
    # FNV-1a of b'lambdipy', computed independently
    h = 0xcbf29ce484222325
    for b in b"lambdipy":
        h = ((h ^ b) * 0x100000001b3) % 2**64
    assert int(out.stdout.strip()) == h


@pytest.mark.slow
def test_numpy_sdist_build(tmp_path):
    """SURVEY.md §9.3's numpy-from-source exemplar. Requires meson-python
    (numpy's PEP 517 backend); this offline image does not ship it, so the
    test documents the gap precisely and runs wherever the backend exists."""
    import shutil

    for mod in ("mesonpy", "Cython"):
        pytest.importorskip(
            mod,
            reason=f"numpy 2.3.5 sdist needs {mod}; not installed in this "
                   "offline image and no network to fetch it (SURVEY.md §8)")
    for tool in ("meson", "ninja"):
        if shutil.which(tool) is None:
            pytest.skip(f"numpy 2.3.5 sdist needs the {tool} binary")
    from lambdipy_tpu.resolve.sources import SourceStore

    recipe = load_recipe_dict({
        "schema": 1, "name": "numpy-src", "version": "2.3.5",
        "build": {"backend": "sdist", "source": "numpy"},
        "prune": {"rules": ["tests", "pycache", "dist-info-extras", "pyi"]},
    })
    result = build_recipe(recipe, tmp_path / "work", sources=SourceStore())
    assert result.smoke_versions.get("numpy")
