"""Device-mesh construction.

Canonical axis names, in nesting order (outermost first — DCN-adjacent axes
outermost, ICI-heavy axes innermost so bandwidth-hungry collectives ride
ICI, per the scaling-book recipe):

- ``dp``   data parallel (pure replication of params, sharded batch)
- ``fsdp`` fully-sharded data parallel (params sharded over batch axis)
- ``pp``   pipeline parallel (stage dimension; lax.ppermute microbatching)
- ``tp``   tensor parallel (heads/mlp/vocab sharded; all-reduce per block)
- ``sp``   sequence/context parallel (ring attention over seq axis)
- ``ep``   expert parallel (MoE expert dimension)
"""

from __future__ import annotations

import contextlib
import contextvars
import math
import warnings

import jax
import numpy as np
from jax.sharding import Mesh

MESH_AXES: tuple[str, ...] = ("dp", "fsdp", "pp", "tp", "sp", "ep")

_ACTIVE_MESH: contextvars.ContextVar[Mesh | None] = contextvars.ContextVar(
    "lambdipy_active_mesh", default=None)


@contextlib.contextmanager
def use_mesh(mesh: Mesh):
    """Enter a mesh for both jax (``with mesh``) and framework consumers
    (:func:`current_mesh` — e.g. models picking a ring-attention backend)."""
    token = _ACTIVE_MESH.set(mesh)
    try:
        with mesh:
            yield mesh
    finally:
        _ACTIVE_MESH.reset(token)


def shard_map_compat(f, *, mesh, in_specs, out_specs):
    """``jax.shard_map`` across jax versions: the public alias only
    landed after 0.4.x; this image's 0.4.37 still spells it
    ``jax.experimental.shard_map.shard_map``. One shim so the manual-
    collective modules (ring / spdecode / pipeline) run on both."""
    sm = getattr(jax, "shard_map", None)
    if sm is not None:
        return sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs)
    from jax.experimental.shard_map import shard_map as sm_exp

    # check_rep=False: the replication checker predates several of the
    # collective patterns used here (psum_scatter in rings, gathered
    # masks) and rejects valid programs on 0.4.x; the new jax path
    # applies its own (correct) checking by default
    return sm_exp(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                  check_rep=False)


def pcast_varying(x, axes):
    """``jax.lax.pcast(x, axes, to="varying")`` where it exists (the
    post-0.4.x vma tracker needs carries marked device-varying), identity
    on 0.4.x — whose shard_map (``check_rep=False`` via
    :func:`shard_map_compat`) tracks no varying types to satisfy.
    Axes the value already varies over are filtered out (pcast rejects
    re-marking them); the ONE home of this compat logic for ring,
    spdecode and pipeline."""
    pcast = getattr(jax.lax, "pcast", None)
    if pcast is None or not axes:
        return x
    have = getattr(jax.typeof(x), "vma", frozenset())
    need = tuple(a for a in axes if a not in have)
    return pcast(x, need, to="varying") if need else x


def current_mesh() -> Mesh | None:
    """The ambient mesh: ours first, then jax's legacy with-mesh context."""
    mesh = _ACTIVE_MESH.get()
    if mesh is not None:
        return mesh
    try:
        with warnings.catch_warnings():
            warnings.simplefilter("ignore", DeprecationWarning)
            from jax.interpreters import pxla

            phys = pxla.thread_resources.env.physical_mesh
        return phys if phys.axis_names else None
    except Exception:
        return None


def make_mesh(shape: dict[str, int], devices=None) -> Mesh:
    """Build a Mesh from {axis: size}; axes absent from ``shape`` get size 1.

    Sizes must multiply to the device count used. ``shape`` values of -1 are
    filled with the remaining device factor (at most one -1).
    """
    devices = list(devices if devices is not None else jax.devices())
    sizes = dict(shape)
    unknown = set(sizes) - set(MESH_AXES)
    if unknown:
        raise ValueError(f"unknown mesh axes {sorted(unknown)}; known: {MESH_AXES}")
    n = len(devices)
    fills = [a for a, s in sizes.items() if s == -1]
    if len(fills) > 1:
        raise ValueError("at most one axis may be -1")
    fixed = math.prod(s for s in sizes.values() if s != -1)
    if fills:
        if n % fixed:
            raise ValueError(f"{n} devices not divisible by fixed axes product {fixed}")
        sizes[fills[0]] = n // fixed
    total = math.prod(sizes.values()) if sizes else 1
    if total != n:
        raise ValueError(
            f"mesh shape {sizes} needs {total} devices, have {n}")
    axis_names = [a for a in MESH_AXES if sizes.get(a, 1) > 1] or ["dp"]
    dims = [sizes.get(a, 1) for a in axis_names]
    arr = np.asarray(devices).reshape(dims)
    return Mesh(arr, axis_names=tuple(axis_names))


def flat_mesh(axis: str = "dp", devices=None) -> Mesh:
    """All devices on a single named axis."""
    devices = list(devices if devices is not None else jax.devices())
    return Mesh(np.asarray(devices), axis_names=(axis,))


def mesh_shape_for(n_devices: int, *, tp: int | None = None,
                   sp: int = 1, pp: int = 1) -> dict[str, int]:
    """Default mesh shape for n devices: fill tp up to 4 (one v5e host's
    worth of ICI-adjacent chips), rest dp. Serving configs override."""
    if tp is None:
        tp = math.gcd(n_devices, 4)
    denom = tp * sp * pp
    if n_devices % denom:
        raise ValueError(f"{n_devices} devices not divisible by tp*sp*pp={denom}")
    return {"dp": n_devices // denom, "pp": pp, "tp": tp, "sp": sp}


def parse_mesh_spec(spec: str) -> dict[str, int]:
    """Parse a serving mesh declaration into ``{axis: size}``.

    The one grammar shared by the ``mesh`` bundle extra, the
    ``LAMBDIPY_MESH`` env var, and ``lambdipy serve --mesh``:

    - ``"tp=2"`` / ``"tp=2,sp=1"`` / ``"dp=2 tp=4"``  explicit axes
      (comma or whitespace separated; axis names from :data:`MESH_AXES`)
    - ``"2"``                                          bare tensor-parallel
      width (the dominant serving shape)
    - ``"2x2"``                                        ``dp x tp`` grid
      (the ROADMAP's bundle shorthand)
    - ``""`` / ``"0"`` / ``"1"`` / ``"off"`` / ``"none"``  no mesh

    Size-1 axes are dropped (they would be omitted from the Mesh anyway);
    an all-size-1 spec means single-device serving and returns ``{}``.
    Unknown axes and non-positive sizes raise ``ValueError`` — a typo'd
    mesh must never silently serve replicated.
    """
    s = (spec or "").strip().lower()
    if s in ("", "0", "1", "off", "none"):
        return {}
    if "x" in s and "=" not in s:
        try:
            dims = [int(tok) for tok in s.split("x")]
        except ValueError:
            raise ValueError(f"unparseable mesh spec {spec!r}") from None
        if len(dims) != 2:
            raise ValueError(
                f"grid mesh spec must be AxB (dp x tp), got {spec!r}")
        shape = {"dp": dims[0], "tp": dims[1]}
    elif "=" not in s:
        try:
            shape = {"tp": int(s)}
        except ValueError:
            raise ValueError(f"unparseable mesh spec {spec!r}") from None
    else:
        shape = {}
        for tok in s.replace(",", " ").split():
            axis, _, val = tok.partition("=")
            if not _ or axis not in MESH_AXES:
                raise ValueError(
                    f"unknown mesh axis {axis!r} in {spec!r}; "
                    f"known: {MESH_AXES}")
            try:
                shape[axis] = int(val)
            except ValueError:
                raise ValueError(
                    f"mesh axis {axis} has non-integer size {val!r}"
                ) from None
    for axis, size in shape.items():
        if size < 1:
            raise ValueError(
                f"mesh axis {axis} must be >= 1, got {size}")
    return {a: n for a, n in shape.items() if n > 1}
