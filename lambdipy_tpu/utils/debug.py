"""Numerics debug checks (SURVEY.md §6 sanitizer row).

The reference is a single-threaded CLI with nothing to sanitize; the
rebuild's analog of a sanitizer is device-side numerics checking: jax's
``debug_nans``/``debug_infs`` modes re-run the offending computation
op-by-op when a NaN/Inf appears in a jit output and raise
``FloatingPointError`` at the producing primitive — the XLA equivalent of
"stop at the first bad write" instead of debugging a poisoned loss ten
steps later.

Two entry points:
- :func:`debug_numerics` — scoped context manager for tests and the
  Trainer (``TrainerConfig.debug_numerics=True``);
- :func:`apply_debug_env` — process-level switch for the serve runtime
  (``LAMBDIPY_DEBUG_NANS=1`` / ``LAMBDIPY_DEBUG_INFS=1`` in a
  deployment's env), applied at bundle boot.

The checks force a device sync per jit call, so they are a debug mode,
never a default.
"""

from __future__ import annotations

import contextlib
import os

from lambdipy_tpu.utils.logs import get_logger

log = get_logger("lambdipy.debug")


@contextlib.contextmanager
def debug_numerics(nans: bool | None = True, infs: bool | None = None):
    """Enable NaN (and optionally Inf) checking for the enclosed scope;
    prior flag values are restored on exit. ``None`` leaves a flag at its
    current value — the context must never silently WEAKEN checking that
    an outer scope (or the env switch) already enabled."""
    import jax

    prior = (jax.config.jax_debug_nans, jax.config.jax_debug_infs)
    if nans is not None:
        jax.config.update("jax_debug_nans", nans)
    if infs is not None:
        jax.config.update("jax_debug_infs", infs)
    # executables compiled before the flag flip can keep serving through
    # the jit fastpath WITHOUT the nan check (observed after meshed
    # workloads); a debug mode can afford the re-trace
    jax.clear_caches()
    try:
        yield
    finally:
        jax.config.update("jax_debug_nans", prior[0])
        jax.config.update("jax_debug_infs", prior[1])


def apply_debug_env() -> dict:
    """Apply LAMBDIPY_DEBUG_NANS / LAMBDIPY_DEBUG_INFS to the process.
    Returns the flags applied (for boot reports). Cheap no-op (jax never
    imported) when neither env var is set, so callers can invoke it
    unconditionally — including for bundles whose payload model is not a
    registered jax family but whose handler uses jax directly."""
    flags = {}
    if os.environ.get("LAMBDIPY_DEBUG_NANS") == "1":
        flags["debug_nans"] = True
    if os.environ.get("LAMBDIPY_DEBUG_INFS") == "1":
        flags["debug_infs"] = True
    if flags:
        import jax

        if flags.get("debug_nans"):
            jax.config.update("jax_debug_nans", True)
        if flags.get("debug_infs"):
            jax.config.update("jax_debug_infs", True)
        jax.clear_caches()  # see debug_numerics: pre-flip executables
        log.warning("numerics debug mode active: %s (per-call device sync; "
                    "not for production serving)", flags)
    return flags
