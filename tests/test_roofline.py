"""Roofline/MFU accounting (utils/roofline.py): the cost models every
published bench/baseline number is related to v5e peak through."""

import dataclasses

import jax.numpy as jnp
import pytest

from lambdipy_tpu.models.llama import LLAMA3_8B, LLAMA_TINY
from lambdipy_tpu.utils import roofline as R


def test_llama_8b_matmul_param_count():
    # Llama-3-8B has ~8.0B params incl. the 0.5B embedding; matmul
    # (embed-excluded) is ~7.5B
    n = R.llama_matmul_params(LLAMA3_8B)
    assert 7.4e9 < n < 7.6e9


def test_matmul_params_match_real_module():
    """The analytic count must equal the actual QDense kernel sizes of an
    initialized model (embed + norm scales are the only non-matmul
    params)."""
    from lambdipy_tpu.models import registry

    adapter = registry.get("llama-tiny").build()
    params = adapter.init_params(seed=0)
    import jax

    total = sum(x.size for x in jax.tree.leaves(params))
    cfg = LLAMA_TINY
    embed = cfg.vocab_size * cfg.hidden
    norms = cfg.layers * 2 * cfg.hidden + cfg.hidden
    assert total == R.llama_matmul_params(cfg) + embed + norms


def test_int8_weight_bytes_half_of_bf16():
    bf16 = R.llama_weight_bytes(LLAMA3_8B)
    int8 = R.llama_weight_bytes(dataclasses.replace(LLAMA3_8B, quant="int8"))
    # int8 stores 1 byte/param vs 2 (scales are per-channel noise)
    assert int8 * 2 == bf16


def test_8b_decode_is_weight_bytes_bound():
    """b1 decode of 8B int8 is HBM-bound: the roofline time equals the
    weight-read time, ~9 ms -> ~108 tok/s upper bound (the number the
    VERDICT's honest-accounting critique predicts)."""
    cfg = dataclasses.replace(LLAMA3_8B, quant="int8")
    c = R.llama_decode_step_cost(cfg, batch=1, cache_len=512)
    t_weights_ms = R.llama_weight_bytes(cfg) / R.V5E_HBM_BYTES_S * 1e3
    assert c.time_lower_bound_ms() == pytest.approx(t_weights_ms, rel=0.05)
    bound = R.llama_decode_tok_s_bound(cfg, batch=1, cache_len=512)
    assert 95 < bound < 115


def test_batching_amortizes_weight_reads():
    cfg = dataclasses.replace(LLAMA3_8B, quant="int8")
    b1 = R.llama_decode_tok_s_bound(cfg, batch=1, cache_len=512)
    b8 = R.llama_decode_tok_s_bound(cfg, batch=8, cache_len=512)
    assert b8 > 6 * b1  # near-linear until KV reads start to matter


def test_kv_quant_halves_cache_traffic():
    cfg = LLAMA3_8B
    q = dataclasses.replace(cfg, kv_quant="int8")
    assert R.llama_kv_bytes_per_pos(q) * 2 == R.llama_kv_bytes_per_pos(cfg)


def test_decode_window_cost_scales_with_active_length():
    """The length-aware decode cost model: a short active window reads
    (and attends) less than the full static window, converging to the
    dense step cost when window == cache_len."""
    cfg = dataclasses.replace(LLAMA3_8B, quant="int8")
    full = R.llama_decode_step_cost(cfg, batch=1, cache_len=8192)
    short = R.llama_decode_window_cost(cfg, batch=1, window_len=512,
                                       active_len=300)
    assert short.hbm_bytes < full.hbm_bytes
    assert short.flops < full.flops
    # KV bytes scale with the window actually read
    kv_full = full.hbm_bytes - R.llama_weight_bytes(cfg)
    kv_short = short.hbm_bytes - R.llama_weight_bytes(cfg)
    assert kv_short == pytest.approx(kv_full * 512 / 8192)
    # window == cache_len degenerates to the dense step cost exactly
    same = R.llama_decode_window_cost(cfg, batch=1, window_len=8192)
    assert (same.flops, same.hbm_bytes) == (full.flops, full.hbm_bytes)


def test_prefill_is_compute_bound_at_1k():
    cfg = dataclasses.replace(LLAMA3_8B, quant="int8")
    c = R.llama_prefill_cost(cfg, batch=1, seq_len=1024)
    assert c.flops / R.V5E_BF16_FLOPS > c.hbm_bytes / R.V5E_HBM_BYTES_S


def test_param_bytes_counts_storage():
    params = {"a": jnp.zeros((4, 4), jnp.int8),
              "b": jnp.zeros((2, 2), jnp.float32)}
    assert R.param_bytes(params) == 16 + 16


def test_utilization_fields():
    c = R.Cost(flops=1e12, hbm_bytes=1e9)
    u = c.utilization(measured_s=0.01)
    # 1e12 FLOP in 10 ms on a 197 TFLOP/s part
    assert u["mfu"] == pytest.approx(1e12 / (0.01 * R.V5E_BF16_FLOPS),
                                     abs=1e-4)
    assert 0 < u["hbm_util"] < 1
    assert u["roofline_ms"] == pytest.approx(
        max(1e12 / R.V5E_BF16_FLOPS, 1e9 / R.V5E_HBM_BYTES_S) * 1e3,
        rel=1e-3)
