"""Host-RAM offload arena for paged KV: the long-context tier's spill
store.

A context past the compiled window used to shed. The offload tier turns
that cliff into a capacity curve: the block table maps a SLIDING view of
a logical context N times the window (``models/llama.py
_lpaged_seg_fn``), and the pages the view slides past are not dropped —
they spill here, to host RAM, as kvwire bytes, so a session failover or
continuation can re-ship the row's FULL logical KV and a page the view
still needs can re-online into the device arena on attention demand.

Three pieces, each host-only:

- :class:`OffloadArena` — the spill store. One page spills as one
  ``LKVC``-shaped body (``runtime/kvwire.py _pack_body`` under a leaf
  template derived ONCE at first use — the hot loop never re-derives it,
  which ``kv.offload.template_encodes`` meters and the tests assert),
  and a batched fetch re-frames the stored bodies into one LKVS/LKVC
  stream decoded by ONE :class:`~lambdipy_tpu.runtime.kvwire
  .StreamDecoder` pass — one frame decode per re-online batch, not per
  page, with every strict wire validation applied before any array
  reaches the device write path.
- :class:`PageTemperature` — the LRU tick tracker pool and store share
  to pick spill victims: hottest pages stay resident, coldest spill
  first.
- :class:`Prefetcher` — the per-row page state machine keyed off the
  decode cursor: pages the NEXT dispatch will need are planned while the
  previous segment is still on the device (dispatch is async — the host
  frame decode hides under device compute), so attention demand finds
  them resident. ``kv.offload.prefetch_hit_rate`` meters how often that
  works; a demand miss stalls the dispatch and is timed.

Failure story: ``offload_stall`` is a first-class ``runtime/faults.py``
site. A slow re-online is a timed stall; a FAILED one (injected
exception, or a key the arena dropped under budget pressure —
:class:`OffloadMiss`) degrades to recomputing the lost KV via prefill —
counted under ``kv.offload.recomputes``, never a wrong token (the
replay is deterministic).
"""

from __future__ import annotations

import itertools
import threading
from typing import Any, Iterable

from lambdipy_tpu.runtime.metrics import KvOffloadStats
from lambdipy_tpu.utils.logs import get_logger

log = get_logger("lambdipy.offload")


class OffloadMiss(KeyError):
    """A fetch asked for a key the arena does not hold (dropped under
    budget pressure, or never spilled). The caller's degradation path is
    prefill recompute — counted, never a wrong token."""


class PageTemperature:
    """Monotonic-tick LRU tracker: ``touch`` on every page use, and
    spill-victim selection asks for the coldest of a candidate set. A
    page never touched ranks coldest of all (tick 0) — fresh state must
    not shield a page from the sweep."""

    def __init__(self):
        self._ticks = itertools.count(1)
        self._last: dict[Any, int] = {}
        self._lock = threading.Lock()

    def touch(self, keys: Iterable[Any]) -> None:
        with self._lock:
            t = next(self._ticks)
            for k in keys:
                self._last[k] = t

    def forget(self, keys: Iterable[Any]) -> None:
        with self._lock:
            for k in keys:
                self._last.pop(k, None)

    def coldest(self, keys: Iterable[Any], n: int) -> list:
        """The ``n`` least-recently-touched of ``keys``, coldest first."""
        with self._lock:
            ranked = sorted(keys, key=lambda k: self._last.get(k, 0))
        return ranked[: max(0, int(n))]

    def snapshot(self) -> dict:
        with self._lock:
            return dict(self._last)


class OffloadArena:
    """Host-RAM page store keyed by caller-chosen ids.

    ``spill`` serializes one page's per-layer block slices into a single
    contiguous kvwire body under the CACHED leaf template (derived once,
    ``template_encodes``-counted); ``fetch_many`` re-frames any set of
    stored pages into one header + chunk stream and decodes it in one
    :class:`~lambdipy_tpu.runtime.kvwire.StreamDecoder` pass. Budget is
    exact stored bytes: a spill past it is REFUSED (counted) and the
    caller drops the page instead — offload is an optimization of the
    degradation path, never a correctness dependency."""

    def __init__(self, *, page: int, layers: int, budget_mb: float = 256.0,
                 stats: KvOffloadStats | None = None, faults: Any = None):
        self.page = int(page)
        self.layers = int(layers)
        self.budget_bytes = max(0, int(float(budget_mb) * 2**20))
        self.stats = stats if stats is not None else KvOffloadStats()
        self.faults = faults  # FaultPlan | None; site "offload_stall"
        self._lock = threading.Lock()
        # key -> (tokens tuple, packed body bytes)
        self._entries: dict[Any, tuple[tuple, bytes]] = {}
        self._bytes = 0
        # leaf template, derived ONCE from the first spilled page (or
        # attached explicitly): [name, dtype, shape] rows + name order
        self._leaves: list | None = None
        self._names: list | None = None

    # -- template ------------------------------------------------------------

    def attach_template(self, leaves) -> None:
        """Install the wire leaf template up front (``[name, dtype name,
        shape]`` rows, e.g. from the prefix store's ``_leaf_template``)
        so even the FIRST spill skips array introspection."""
        self._leaves = [[str(n), str(d), [int(x) for x in s]]
                        for n, d, s in leaves]
        self._names = [n for n, _, _ in self._leaves]
        self.stats.record_template_encode()

    def _ensure_template(self, block) -> None:
        if self._leaves is None:
            from lambdipy_tpu.runtime.kvwire import _leaf_template_of

            self._leaves = _leaf_template_of(block)
            self._names = [n for n, _, _ in self._leaves]
            self.stats.record_template_encode()

    # -- spill ---------------------------------------------------------------

    def spill(self, key, tokens, block) -> bool:
        """Store one page (``block`` = per-layer leaf-dict list shaped
        like ``models/llama.py arena_page_slices`` returns; ``tokens``
        its logical token ids). Returns False on budget refusal —
        caller drops the page and counts the loss."""
        from lambdipy_tpu.runtime.kvwire import pack_block_body

        toks = tuple(int(t) for t in tokens)
        if len(toks) != self.page:
            raise ValueError(
                f"spill of {len(toks)} tokens into a {self.page}-token "
                f"page")
        self._ensure_template(block)
        body = pack_block_body([block], self._names)
        with self._lock:
            old = self._entries.get(key)
            new_bytes = self._bytes + len(body) \
                - (len(old[1]) if old else 0)
            if self.budget_bytes and new_bytes > self.budget_bytes:
                self.stats.record_spill_refusal()
                return False
            self._entries[key] = (toks, body)
            self._bytes = new_bytes
        self.stats.record_spill(1, len(body))
        return True

    # -- fetch ---------------------------------------------------------------

    def fetch_many(self, keys) -> list:
        """Batched re-online read: the stored bodies of ``keys``
        re-framed into ONE LKVS/LKVC stream (header bytes from the
        cached template — zero re-encode of live arrays) and decoded in
        one strictly-validating pass. Returns one block per key, in
        order. Raises :class:`OffloadMiss` for an absent key and
        whatever an armed ``offload_stall`` fault injects (the caller's
        recompute path)."""
        keys = list(keys)
        if not keys:
            return []
        if self.faults is not None:
            self.faults.check("offload_stall")
        from lambdipy_tpu.runtime.kvwire import (
            decode_stream,
            encode_chunk_packed,
            encode_stream_header,
        )

        with self._lock:
            entries = []
            for k in keys:
                e = self._entries.get(k)
                if e is None:
                    raise OffloadMiss(k)
                entries.append(e)
        tokens = [t for toks, _ in entries for t in toks]
        frames = [encode_stream_header(tokens, self.page, self.layers,
                                       self._leaves)]
        frames += [encode_chunk_packed(i, 1, body)
                   for i, (_, body) in enumerate(entries)]
        _, _, blocks = decode_stream(frames)
        self.stats.record_reonline(len(keys), batches=1, decodes=1)
        return blocks

    def frames(self, keys) -> list[bytes]:
        """The stored pages of ``keys`` as wire-ready LKVS/LKVC frames
        (header + one chunk per page) — the failover re-ship read: a
        partially-offloaded row ships its cold pages straight from host
        RAM, no device round trip."""
        from lambdipy_tpu.runtime.kvwire import (
            encode_chunk_packed,
            encode_stream_header,
        )

        keys = list(keys)
        with self._lock:
            entries = []
            for k in keys:
                e = self._entries.get(k)
                if e is None:
                    raise OffloadMiss(k)
                entries.append(e)
        tokens = [t for toks, _ in entries for t in toks]
        out = [encode_stream_header(tokens, self.page, self.layers,
                                    self._leaves)]
        out += [encode_chunk_packed(i, 1, body)
                for i, (_, body) in enumerate(entries)]
        return out

    # -- bookkeeping ---------------------------------------------------------

    def contains(self, key) -> bool:
        with self._lock:
            return key in self._entries

    def tokens_of(self, key) -> tuple:
        with self._lock:
            e = self._entries.get(key)
            if e is None:
                raise OffloadMiss(key)
            return e[0]

    def drop(self, keys) -> int:
        dropped = 0
        with self._lock:
            for k in list(keys):
                e = self._entries.pop(k, None)
                if e is not None:
                    self._bytes -= len(e[1])
                    dropped += 1
        if dropped:
            self.stats.record_drop(dropped)
        return dropped

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def gauges(self) -> dict:
        with self._lock:
            return {"offloaded_pages": len(self._entries),
                    "offloaded_bytes": self._bytes,
                    "offload_budget_bytes": self.budget_bytes}

    def report(self) -> dict:
        """Gauges + counters — the ``kv.offload`` metrics block."""
        out = self.gauges()
        out.update(self.stats.report())
        return out


# Prefetcher page states: absent from the map = the page was never
# offloaded (always resident — not a prefetch hit, not a miss; only
# pages that LEFT the device count toward the hit rate).
OFFLOADED = "offloaded"
INFLIGHT = "inflight"
RESIDENT = "resident"


class Prefetcher:
    """Per-row page-residency state machine, keyed off the decode
    cursor.

    The runner drives it: ``spill(keys)`` when the view slides or a
    parked row's pages yield to pressure; ``plan(upcoming)`` right
    AFTER dispatching a segment (returns the offloaded subset of the
    pages the NEXT dispatch will need, marked inflight — the caller
    fetches them while the device is busy, then ``complete(keys)``);
    ``demand(needed)`` right BEFORE the next dispatch (counts hits —
    pages prefetch already brought home — vs misses, which the caller
    must now fetch synchronously, stalling the dispatch)."""

    def __init__(self, stats: KvOffloadStats | None = None):
        self.stats = stats if stats is not None else KvOffloadStats()
        self._state: dict[Any, str] = {}

    def state(self, key) -> str:
        return self._state.get(key, RESIDENT)

    def spill(self, keys) -> None:
        for k in keys:
            self._state[k] = OFFLOADED

    def plan(self, upcoming) -> list:
        """Offloaded pages among ``upcoming``, marked inflight."""
        todo = [k for k in upcoming if self._state.get(k) == OFFLOADED]
        for k in todo:
            self._state[k] = INFLIGHT
        return todo

    def complete(self, keys) -> None:
        """Fetched-and-written pages come home resident."""
        for k in keys:
            if k in self._state:
                self._state[k] = RESIDENT

    def demand(self, needed) -> list:
        """Residency check at dispatch time. Returns the keys STILL not
        resident (the caller fetches them now — a timed stall) and
        records the hit/miss split: a page that went offloaded and is
        resident again by demand time is a prefetch hit. Each spill
        scores at most ONE hit — a hit key leaves the tracker, so a page
        that stays resident for fifty more segments doesn't inflate the
        rate fifty-fold."""
        needed = list(needed)
        misses = [k for k in needed
                  if self._state.get(k) in (OFFLOADED, INFLIGHT)]
        hit_keys = [k for k in needed
                    if self._state.get(k) == RESIDENT]
        self.stats.record_prefetch(len(hit_keys), len(misses))
        for k in hit_keys:
            del self._state[k]
        for k in misses:
            self._state[k] = INFLIGHT
        return misses

    def forget(self, keys) -> None:
        for k in keys:
            self._state.pop(k, None)

    def counts(self) -> dict:
        out = {OFFLOADED: 0, INFLIGHT: 0, RESIDENT: 0}
        for s in self._state.values():
            out[s] += 1
        return out
