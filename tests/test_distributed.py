"""Multi-host bootstrap + hybrid mesh construction + train checkpoint
resume (single-process exercises of the multi-host code paths)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from lambdipy_tpu.parallel.distributed import (
    DistributedContext,
    initialize_from_env,
    make_hybrid_mesh,
    process_batch_slice,
)
from lambdipy_tpu.parallel.mesh import make_mesh, use_mesh


def test_initialize_noop_single_process(monkeypatch):
    for var in ("LAMBDIPY_COORDINATOR", "JAX_COORDINATOR_ADDRESS",
                "LAMBDIPY_NUM_PROCESSES", "JAX_NUM_PROCESSES"):
        monkeypatch.delenv(var, raising=False)
    ctx = initialize_from_env()
    assert ctx == DistributedContext(False, 0, 1, None)
    assert ctx.is_primary


def test_initialize_ignores_single_process_env(monkeypatch):
    monkeypatch.setenv("LAMBDIPY_COORDINATOR", "localhost:1234")
    monkeypatch.setenv("LAMBDIPY_NUM_PROCESSES", "1")
    ctx = initialize_from_env()
    assert not ctx.initialized
    assert ctx.coordinator == "localhost:1234"


def test_hybrid_mesh_single_slice(cpu_devices):
    mesh = make_hybrid_mesh({"dp": 2, "tp": 4})
    assert mesh.axis_names == ("dp", "tp")
    assert dict(mesh.shape) == {"dp": 2, "tp": 4}
    # DCN-ready ordering: tp (innermost) varies fastest over raw devices
    arr = np.asarray(mesh.devices)
    assert [d.id for d in arr[0]] == [0, 1, 2, 3]


def test_hybrid_mesh_dcn_factor(cpu_devices):
    """dcn dp=2 over ici tp=4: each 'slice' (process-contiguous block)
    holds one tp group."""
    mesh = make_hybrid_mesh({"tp": 4}, {"dp": 2})
    assert mesh.axis_names == ("dp", "tp")
    assert dict(mesh.shape) == {"dp": 2, "tp": 4}


def test_hybrid_mesh_validation(cpu_devices):
    with pytest.raises(ValueError):
        make_hybrid_mesh({"xx": 8})
    with pytest.raises(ValueError):
        make_hybrid_mesh({"dp": 3})  # 3 != 8 devices


def test_hybrid_mesh_runs_collectives(cpu_devices):
    """A psum over the hybrid mesh produces correct numbers."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = make_hybrid_mesh({"dp": 2, "tp": 4})
    x = jnp.arange(8.0)
    with mesh:
        xs = jax.device_put(x.reshape(2, 4), NamedSharding(mesh, P("dp", "tp")))
        total = jax.jit(jnp.sum)(xs)
    assert float(total) == float(x.sum())


def test_process_batch_slice():
    local, offset = process_batch_slice(32)
    assert (local, offset) == (32, 0)
    # explicit multi-process overrides exercise the slicing + the guard
    assert process_batch_slice(32, process_index=3, process_count=4) == (8, 24)
    with pytest.raises(ValueError):
        process_batch_slice(33, process_index=0, process_count=2)


@pytest.mark.slow  # heavyweight parity; subsystem keeps a fast test
def test_train_checkpoint_resume(tmp_path, cpu_devices):
    """Save at steps 1..3, restore latest into a fresh run, training
    continues with identical state (SURVEY.md §6 checkpoint/resume row)."""
    from lambdipy_tpu.models import registry
    from lambdipy_tpu.train.checkpoint import TrainCheckpointer
    from lambdipy_tpu.train.step import sharded_train_step

    adapter = registry.get("llama-tiny").build()
    params = adapter.init_params(seed=0)
    mesh = make_mesh({"dp": 2, "tp": 2}, devices=jax.devices()[:4])
    tokens = jnp.asarray(np.random.default_rng(0).integers(0, 500, (4, 16)),
                         jnp.int32)

    with use_mesh(mesh):
        step, state, batch_sharding = sharded_train_step(
            adapter.forward, params, mesh, adapter.tp_rules)
        batch = jax.device_put(tokens, batch_sharding)
        with TrainCheckpointer(tmp_path / "ckpt", max_to_keep=2) as ckpt:
            for i in range(1, 4):
                state, _ = step(state, batch)
                assert ckpt.save(i, state)
        final_params = jax.device_get(state.params)

    ckpt2 = TrainCheckpointer(tmp_path / "ckpt")
    assert ckpt2.latest_step() == 3
    assert ckpt2.all_steps() == [2, 3]  # retention pruned step 1

    with use_mesh(mesh):
        step2, state2, batch_sharding2 = sharded_train_step(
            adapter.forward, params, mesh, adapter.tp_rules)
        restored, at = ckpt2.restore(state2)
        assert at == 3
        assert int(restored.step) == 3
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_array_equal(
                np.asarray(jax.device_get(a)), np.asarray(b)),
            restored.params, final_params)
        # resumed training takes a step without recompiling state shapes
        state3, metrics = step2(restored, jax.device_put(tokens, batch_sharding2))
        assert int(state3.step) == 4
        assert np.isfinite(float(metrics["loss"]))
    ckpt2.close()


def test_checkpoint_empty_dir(tmp_path):
    from lambdipy_tpu.train.checkpoint import TrainCheckpointer

    ckpt = TrainCheckpointer(tmp_path / "empty")
    state, step = ckpt.restore({"a": jnp.zeros((2,))})
    assert state is None and step is None
    ckpt.close()
