"""Pure decision logic for the elastic fleet control plane.

The controller (fleet/controller.py) scrapes the fleet's published
signals every tick and asks :func:`decide` what to do about them. This
module is deliberately free of I/O, clocks, and randomness: a decision
is a pure function of (:class:`Snapshot`, :class:`PolicyState`,
:class:`PolicyConfig`) — the same inputs always produce the same
actions, which is what makes the bench's byte-identical decision-trace
re-run possible and keeps every rule unit-testable as a table of
snapshots.

Signals -> actuators (ROADMAP direction 2):

- fleet-level per-class queue-wait P99 (the router's ``fleet.queue_wait``
  aggregate) vs the SLO target drives the LIFECYCLE actions:
  promote a mixed replica to the prefill class (drain + session re-ship
  is the safe migration primitive), spawn a new replica when there is
  nothing left to promote, and demote/retire on sustained idleness;
- per-replica ``batching.pipeline`` (``overlap_ratio``,
  ``fetch_block_s``/``wall_s``) drives the ``pipeline_depth`` knob;
- per-replica ``batching.spec`` acceptance EWMA drives ``spec_k``;
- the router's ``ship_ms_ewma`` drives ``--ship-window`` — one config
  serves both the loopback and the 66 ms-RTT transport.

Two dampers keep the loop from flapping:

- HYSTERESIS: the SLO comparison is a band, not a line. A breach only
  starts above ``slo * (1 + hysteresis)``, the all-clear only below
  ``slo * (1 - hysteresis)``, and a signal inside the band sustains
  NEITHER (both timers reset) — a boundary-straddling P99 produces no
  actions at all. Knob rules get the same treatment from their
  high/low band pairs.
- COOLDOWN: at most one lifecycle action per
  ``lifecycle_cooldown_s``, and each (target, knob) pair waits
  ``knob_cooldown_s`` between retunes, so the loop observes the effect
  of an action before stacking another on top of it.

Safety invariant (fuzz-tested): no decision sequence may drop the
routable decode-serving set (decode + mixed classes) below
``live_floor`` — promote and retire both refuse when the post-action
count would cross it.
"""

from __future__ import annotations

from dataclasses import asdict, dataclass, field

PREFILL = "prefill"
DECODE = "decode"
MIXED = "mixed"

# action kinds, in the order ties are broken: one lifecycle action per
# tick, knob retunes ride along freely
PROMOTE = "promote"
DEMOTE = "demote"
SPAWN = "spawn"
RETIRE = "retire"
SET_KNOB = "set_knob"
LIFECYCLE = (PROMOTE, DEMOTE, SPAWN, RETIRE)

ROUTER = "router"  # the knob target that is the router, not a replica


@dataclass(frozen=True)
class ReplicaView:
    """What the policy may know about one replica. ``None`` for a
    signal means the replica does not publish it (no continuous
    engine, spec off, metrics scrape failed) — every rule skips a
    ``None`` rather than guessing."""

    name: str
    role: str = MIXED
    routable: bool = True
    managed: bool = False          # pool-owned: retire is possible
    outstanding: int = 0
    pipeline_depth: int | None = None
    overlap_ratio: float | None = None
    fetch_frac: float | None = None   # fetch_block_s / wall_s
    spec_k: int | None = None
    acceptance: float | None = None   # batching.spec acceptance_rate
    # draft tier (batching.spec.draft): the engine's current provider
    # default and the MODEL provider's acceptance EWMA — the signal the
    # demote rule watches for a collapsed self-draft head
    draft_mode: str | None = None
    draft_acceptance: float | None = None
    # long-context tier (batching.long_context): the re-online stall
    # share of engine wall and the decode-cursor prefetch hit rate
    # drive the max_logical_ctx retune; the compiled window bounds it
    # below, the boot-time cap bounds the restore above
    offload_stall_frac: float | None = None
    prefetch_hit_rate: float | None = None
    max_logical_ctx: int | None = None
    compiled_window: int | None = None
    boot_logical_ctx: int | None = None


@dataclass(frozen=True)
class Snapshot:
    """One tick's view of the fleet — everything :func:`decide` may
    read. ``t`` is the controller's clock (seconds since it started):
    the policy never reads a wall clock of its own, so replaying a
    recorded snapshot sequence replays the decisions bit-for-bit."""

    t: float
    replicas: tuple[ReplicaView, ...] = ()
    queue_wait_p99_ms: dict = field(default_factory=dict)  # class -> ms
    util: dict = field(default_factory=dict)               # class -> EWMA
    ship_ms_ewma: float = 0.0
    ships: int = 0
    ship_window: int = 0
    can_spawn: bool = False

    def to_dict(self) -> dict:
        return asdict(self)


@dataclass(frozen=True)
class PolicyConfig:
    """Operator surface for the control loop; every field has a
    serving-safe default. ``slo_p99_ms`` grades the ``slo_class``
    lane's fleet-level queue-wait P99."""

    slo_p99_ms: float = 250.0
    slo_class: str = "interactive"
    hysteresis: float = 0.25       # fractional band around the SLO
    sustain_s: float = 5.0         # breach/clear must hold this long
    lifecycle_cooldown_s: float = 30.0
    knob_cooldown_s: float = 10.0
    live_floor: int = 1            # min routable decode-serving replicas
    min_replicas: int = 1
    max_replicas: int = 8
    max_prefill: int = 2           # prefill replicas carved from the pool
    util_low: float = 0.25         # idle band for demote/retire
    # pipeline_depth: deepen while the host is visibly blocked fetching
    # (fetch stall share of engine wall) and the device is not already
    # fully overlapped; shrink when fetching costs ~nothing
    depth_min: int = 1
    depth_max: int = 4
    fetch_frac_high: float = 0.25
    fetch_frac_low: float = 0.02
    overlap_high: float = 0.95
    # spec_k: widen while drafts keep being accepted, narrow when the
    # verify work is mostly thrown away (k stays a pow-2 like the
    # engine's own bucketing; never turned on/off here — only resized)
    spec_k_min: int = 2
    spec_k_max: int = 8
    acceptance_high: float = 0.8
    acceptance_low: float = 0.4
    # draft_mode: demote the engine DEFAULT model -> lookup when the
    # model provider's acceptance EWMA collapses below the floor (the
    # per-row fallback already protects in-flight rows one by one; this
    # stops NEW rows from re-paying the discovery). Never promoted
    # lookup -> model here: that is an operator/boot decision.
    draft_acceptance_floor: float = 0.2
    # ship_window: more frames in flight when the transfer is slow
    # (ship latency EWMA prices the transport), fewer when it is ~free
    ship_window_min: int = 2
    ship_window_max: int = 16
    ship_ms_high: float = 50.0
    ship_ms_low: float = 5.0
    # max_logical_ctx: halve the admitted logical window while
    # re-online stalls eat a visible share of engine wall (the offload
    # tier is thrashing — rows slide more history than the host arena
    # can re-online in time), double it back toward the boot cap on
    # clean windows. The band (high/low) plus the per-knob cooldown is
    # the damping; the compiled window is the hard floor (below it the
    # runner cannot serve at all).
    stall_frac_high: float = 0.10
    stall_frac_low: float = 0.02
    prefetch_hit_floor: float = 0.5


@dataclass
class PolicyState:
    """The loop's memory, carried explicitly between ticks so
    :func:`decide` stays pure. ``breach_since``/``clear_since`` are the
    sustained-signal timers; the cooldown maps key on action family
    and ``target:knob``."""

    breach_since: float | None = None
    clear_since: float | None = None
    last_lifecycle_t: float | None = None
    last_knob_t: dict = field(default_factory=dict)  # "target:knob" -> t
    ticks: int = 0


@dataclass(frozen=True)
class Action:
    """One decision. ``kind`` is a lifecycle verb or ``set_knob``;
    ``target`` is a replica name (or ``router`` for the ship window);
    ``reason`` carries the signal that justified it, for the decision
    trace and the nemesis-visible event log."""

    kind: str
    target: str
    role: str | None = None        # spawn/promote/demote: the new class
    knob: str | None = None
    value: int | float | str | None = None   # str: e.g. draft_mode
    reason: str = ""

    def render(self) -> str:
        parts = [self.kind, self.target]
        if self.role is not None:
            parts.append(f"role={self.role}")
        if self.knob is not None:
            parts.append(f"{self.knob}={self.value}")
        if self.reason:
            parts.append(f"({self.reason})")
        return " ".join(parts)


def _next_pow2(n: int, *, up: bool) -> int:
    """The neighbouring power of two: knob steps stay on the engine's
    own pow-2 buckets so a retune never forces a fresh program shape
    outside the bucketed set."""
    n = max(1, int(n))
    p = 1
    while p < n:
        p *= 2
    if up:
        return p * 2 if p <= n else p
    return max(1, p // 2 if p >= n else p)


def _update_slo_timers(snap: Snapshot, state: PolicyState,
                       cfg: PolicyConfig) -> None:
    """Hysteresis core: the breach timer runs only above the high
    band, the clear timer only below the low band, and the band
    between them resets BOTH — straddling the boundary can never
    accumulate sustain in either direction."""
    p99 = snap.queue_wait_p99_ms.get(cfg.slo_class)
    high = cfg.slo_p99_ms * (1.0 + cfg.hysteresis)
    low = cfg.slo_p99_ms * (1.0 - cfg.hysteresis)
    if p99 is not None and p99 > high:
        if state.breach_since is None:
            state.breach_since = snap.t
        state.clear_since = None
    elif p99 is not None and p99 < low:
        if state.clear_since is None:
            state.clear_since = snap.t
        state.breach_since = None
    else:  # inside the band, or no samples yet: no evidence either way
        state.breach_since = None
        state.clear_since = None


def _sustained(since: float | None, now: float, need_s: float) -> bool:
    return since is not None and (now - since) >= need_s


def _knob_ready(state: PolicyState, key: str, now: float,
                cooldown_s: float) -> bool:
    last = state.last_knob_t.get(key)
    return last is None or (now - last) >= cooldown_s


def _lifecycle(snap: Snapshot, state: PolicyState,
               cfg: PolicyConfig) -> Action | None:
    """At most one lifecycle action per tick (and per cooldown
    window): capacity moves one replica at a time so the next
    snapshot shows the effect before the loop moves again."""
    if state.last_lifecycle_t is not None and \
            (snap.t - state.last_lifecycle_t) < cfg.lifecycle_cooldown_s:
        return None
    live = [r for r in snap.replicas if r.routable]
    serving = [r for r in live if r.role in (DECODE, MIXED)]
    prefill = [r for r in live if r.role == PREFILL]
    mixed = sorted((r for r in live if r.role == MIXED),
                   key=lambda r: (r.outstanding, r.name))
    p99 = snap.queue_wait_p99_ms.get(cfg.slo_class)

    if _sustained(state.breach_since, snap.t, cfg.sustain_s):
        reason = (f"{cfg.slo_class} p99 {p99:.0f}ms > slo "
                  f"{cfg.slo_p99_ms:.0f}ms for "
                  f"{snap.t - state.breach_since:.1f}s")
        # promote first: carving a prefill replica out of the mixed
        # pool is free capacity ISOLATION (the burstable phase moves
        # off the decode path) and reversible; spawning is neither
        if mixed and len(prefill) < cfg.max_prefill \
                and len(serving) - 1 >= cfg.live_floor:
            return Action(kind=PROMOTE, target=mixed[0].name,
                          role=PREFILL, reason=reason)
        if snap.can_spawn and len(live) < cfg.max_replicas:
            return Action(kind=SPAWN, target="", role=MIXED,
                          reason=reason)
        return None

    if _sustained(state.clear_since, snap.t, cfg.sustain_s):
        reason = (f"{cfg.slo_class} p99 "
                  f"{p99 if p99 is None else round(p99)}ms < slo "
                  f"{cfg.slo_p99_ms:.0f}ms for "
                  f"{snap.t - state.clear_since:.1f}s")
        # demote before retire: give capacity back to the decode path
        # first, only then shrink the fleet — and only when the class
        # being shed is demonstrably idle
        if prefill and snap.util.get(PREFILL, 1.0) < cfg.util_low:
            cand = sorted(prefill, key=lambda r: (r.outstanding, r.name))
            return Action(kind=DEMOTE, target=cand[0].name, role=MIXED,
                          reason=f"{reason}, prefill util "
                                 f"{snap.util.get(PREFILL, 0.0):.2f}")
        serving_util = max((snap.util.get(c, 0.0) for c in (DECODE,
                                                            MIXED)),
                           default=0.0)
        retirable = sorted(
            (r for r in serving if r.managed and r.outstanding == 0),
            key=lambda r: r.name)
        if retirable and serving_util < cfg.util_low \
                and len(live) > cfg.min_replicas \
                and len(serving) - 1 >= cfg.live_floor:
            return Action(kind=RETIRE, target=retirable[0].name,
                          reason=f"{reason}, serving util "
                                 f"{serving_util:.2f}")
    return None


def _knobs(snap: Snapshot, state: PolicyState,
           cfg: PolicyConfig) -> list[Action]:
    actions: list[Action] = []

    def emit(target: str, knob: str, value, reason: str) -> None:
        key = f"{target}:{knob}"
        if _knob_ready(state, key, snap.t, cfg.knob_cooldown_s):
            state.last_knob_t[key] = snap.t
            actions.append(Action(kind=SET_KNOB, target=target,
                                  knob=knob, value=value, reason=reason))

    for r in sorted(snap.replicas, key=lambda r: r.name):
        if not r.routable:
            continue
        # pipeline_depth from the pipeline's own overlap accounting
        if r.pipeline_depth is not None and r.fetch_frac is not None \
                and r.overlap_ratio is not None:
            if r.fetch_frac > cfg.fetch_frac_high \
                    and r.overlap_ratio < cfg.overlap_high \
                    and r.pipeline_depth < cfg.depth_max:
                emit(r.name, "pipeline_depth", r.pipeline_depth + 1,
                     f"fetch stall {r.fetch_frac:.2f} of wall, "
                     f"overlap {r.overlap_ratio:.2f}")
            elif r.fetch_frac < cfg.fetch_frac_low \
                    and r.pipeline_depth > cfg.depth_min:
                emit(r.name, "pipeline_depth", r.pipeline_depth - 1,
                     f"fetch stall {r.fetch_frac:.2f} of wall")
        # spec_k from the live acceptance EWMA (resize only: a replica
        # that stood spec down, or never ran it, publishes no k)
        if r.spec_k is not None and r.spec_k >= 2 \
                and r.acceptance is not None:
            if r.acceptance > cfg.acceptance_high \
                    and r.spec_k < cfg.spec_k_max:
                emit(r.name, "spec_k",
                     min(cfg.spec_k_max, _next_pow2(r.spec_k, up=True)),
                     f"acceptance {r.acceptance:.2f}")
            elif r.acceptance < cfg.acceptance_low \
                    and r.spec_k > cfg.spec_k_min:
                emit(r.name, "spec_k",
                     max(cfg.spec_k_min, _next_pow2(r.spec_k, up=False)),
                     f"acceptance {r.acceptance:.2f}")
        # draft_mode: demote the engine default model -> lookup when
        # the self-draft head's acceptance EWMA has collapsed — new
        # rows stop paying the draft forward at all, instead of each
        # rediscovering the collapse through its own per-row fallback
        if r.draft_mode in ("model", "aux") \
                and r.spec_k is not None and r.spec_k >= 2 \
                and r.draft_acceptance is not None \
                and r.draft_acceptance < cfg.draft_acceptance_floor:
            emit(r.name, "draft_mode", "lookup",
                 f"draft acceptance {r.draft_acceptance:.2f} < "
                 f"{cfg.draft_acceptance_floor:.2f}")
        # max_logical_ctx from the offload tier's own stall accounting:
        # step DOWN (halve, floored at the compiled window) while
        # re-online stalls are a sustained share of wall — or while the
        # prefetcher is missing most demands and stalls are already
        # above the clean band; step back UP (double, capped at the
        # boot value) once the window runs clean. The replica publishes
        # nothing without a live long-context runner — rule skipped.
        if r.max_logical_ctx is not None \
                and r.compiled_window is not None \
                and r.compiled_window > 0 \
                and r.offload_stall_frac is not None:
            boot = r.boot_logical_ctx or r.max_logical_ctx
            thrash = r.offload_stall_frac > cfg.stall_frac_high or (
                r.prefetch_hit_rate is not None
                and r.prefetch_hit_rate < cfg.prefetch_hit_floor
                and r.offload_stall_frac > cfg.stall_frac_low)
            if thrash and r.max_logical_ctx > r.compiled_window:
                hit = ("n/a" if r.prefetch_hit_rate is None
                       else f"{r.prefetch_hit_rate:.2f}")
                emit(r.name, "max_logical_ctx",
                     max(r.compiled_window, r.max_logical_ctx // 2),
                     f"reonline stall {r.offload_stall_frac:.3f} of "
                     f"wall, prefetch hit {hit}")
            elif r.offload_stall_frac < cfg.stall_frac_low \
                    and r.max_logical_ctx < boot:
                emit(r.name, "max_logical_ctx",
                     min(boot, r.max_logical_ctx * 2),
                     f"reonline stall {r.offload_stall_frac:.3f} of "
                     f"wall (clean)")
    # the router's ship window from the ship-latency EWMA — only once
    # real ships have priced the transport
    if snap.ships > 0 and snap.ship_window > 0:
        if snap.ship_ms_ewma > cfg.ship_ms_high \
                and snap.ship_window < cfg.ship_window_max:
            emit(ROUTER, "ship_window",
                 min(cfg.ship_window_max,
                     _next_pow2(snap.ship_window, up=True)),
                 f"ship {snap.ship_ms_ewma:.1f}ms ewma")
        elif snap.ship_ms_ewma < cfg.ship_ms_low \
                and snap.ship_window > cfg.ship_window_min:
            emit(ROUTER, "ship_window",
                 max(cfg.ship_window_min,
                     _next_pow2(snap.ship_window, up=False)),
                 f"ship {snap.ship_ms_ewma:.1f}ms ewma")
    return actions


def decide(snap: Snapshot, state: PolicyState,
           cfg: PolicyConfig) -> list[Action]:
    """One tick's decisions. Mutates ``state`` (the explicit memory the
    caller carries between ticks) and returns the actions in a
    deterministic order: the single lifecycle action (if any) first,
    then knob retunes sorted by target name."""
    state.ticks += 1
    _update_slo_timers(snap, state, cfg)
    actions: list[Action] = []
    act = _lifecycle(snap, state, cfg)
    if act is not None:
        state.last_lifecycle_t = snap.t
        # a lifecycle action resets the sustain timers: the next
        # breach/clear must re-accumulate against the NEW fleet shape
        state.breach_since = None
        state.clear_since = None
        actions.append(act)
    actions.extend(_knobs(snap, state, cfg))
    return actions
