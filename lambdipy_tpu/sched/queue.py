"""Bounded request queue with per-class FIFO lanes.

Three request classes cover the serving workloads the roadmap names:
``interactive`` (latency-sensitive user traffic), ``batch`` (bulk
offline inference) and ``background`` (warmers, evals — anything that
should only ride spare capacity). Each class is one FIFO lane; the
dequeue *order between* lanes belongs to the policy
(:mod:`lambdipy_tpu.sched.policy`), so the queue itself stays a dumb,
bounded container that a policy can never corrupt.
"""

from __future__ import annotations

import itertools
import time
from collections import deque
from dataclasses import dataclass, field

CLASSES = ("interactive", "batch", "background")

_seq = itertools.count()


@dataclass
class Ticket:
    """One admitted request's place in line."""

    cls: str = "interactive"
    tenant: str = "anon"
    deadline_ms: float | None = None
    cost_ms: float = 0.0           # estimator's service estimate at admit
    prefill_tokens: int = 0
    decode_tokens: int = 0
    seq: int = field(default_factory=lambda: next(_seq))
    enqueued: float = field(default_factory=time.monotonic)
    granted: bool = False
    expired: bool = False          # deadline shed after admission
    wait_ms: float | None = None   # actual queue wait, stamped at grant


class RequestQueue:
    """FIFO lanes under one total bound. Not thread-safe on its own —
    the Scheduler serializes access under its condition lock."""

    def __init__(self, capacity: int = 64):
        self.capacity = max(1, capacity)
        self._lanes: dict[str, deque[Ticket]] = {c: deque() for c in CLASSES}

    def depth(self, cls: str | None = None) -> int:
        if cls is not None:
            return len(self._lanes[cls])
        return sum(len(q) for q in self._lanes.values())

    def full(self) -> bool:
        return self.depth() >= self.capacity

    def push(self, ticket: Ticket) -> bool:
        if self.full():
            return False
        self._lanes[ticket.cls].append(ticket)
        return True

    def pop(self, policy) -> Ticket | None:
        """Dequeue the next ticket; *which lane* is the policy's call."""
        nonempty = {c: q for c, q in self._lanes.items() if q}
        if not nonempty:
            return None
        cls = policy.select(nonempty)
        return self._lanes[cls].popleft()

    def remove(self, ticket: Ticket) -> bool:
        """Withdraw a parked ticket (wait timeout / client gone)."""
        try:
            self._lanes[ticket.cls].remove(ticket)
            return True
        except ValueError:
            return False

    def snapshot(self) -> dict[str, int]:
        return {c: len(q) for c, q in self._lanes.items()}
