"""Shared base layers: the TPU answer to Lambda's 250 MB cap.

SURVEY.md §3.3 consequence: libtpu.so alone is 614 MB and
``jaxlib/libjax_common.so`` 308 MB, so TPU bundles cannot meet a
Lambda-style size cap. Instead the runtime image ships a shared,
content-addressed base layer (the analogue of AWS Lambda layers the
reference's users attach), and per-function bundles carry only their delta.
A base layer is a named set of distributions the runtime guarantees.

At serve time the base layer resolves to the host environment's
site-packages (this machine's /opt/venv **is** the jax-tpu base image — it
matches ``jss:tpu/Dockerfile:43-94``'s userland, SURVEY.md §3.4). The
manifest records the exact versions the bundle was built against so the
runtime can detect skew.
"""

from __future__ import annotations

import importlib.metadata
import site
import sys
from pathlib import Path

# Distribution sets per layer. Versions are recorded at build time, not here,
# so layers stay valid across image updates (skew is detected, not assumed).
BASE_LAYERS: dict[str, tuple[str, ...]] = {
    "none": (),
    # The jax TPU serving stack (jss:tpu/Dockerfile userland, SURVEY.md §3.4)
    "jax-tpu": (
        "jax", "jaxlib", "libtpu", "numpy", "ml-dtypes", "opt-einsum", "scipy",
        "flax", "optax", "chex", "orbax-checkpoint", "msgpack", "einops",
        "absl-py", "etils", "typing-extensions", "rich", "pyyaml",
        "tensorstore", "protobuf", "treescope", "humanize", "fsspec",
        "importlib-resources", "zipp", "nest-asyncio", "simplejson", "toolz",
        "markdown-it-py", "mdurl", "pygments", "setuptools", "wheel",
        "aiofiles", "ordered-set",
    ),
    # CPU scientific stack for configs 1-2 style bundles that opt in
    "sci-cpu": ("numpy", "scipy", "scikit-learn", "joblib", "threadpoolctl"),
    # torch CPU/XLA stack for config 4
    "torch": ("torch", "numpy", "typing-extensions", "sympy", "networkx",
              "jinja2", "markupsafe", "filelock", "fsspec", "mpmath"),
}


def base_layer_dists(name: str) -> set[str]:
    try:
        return set(BASE_LAYERS[name])
    except KeyError:
        raise KeyError(f"unknown base layer {name!r}; known: {sorted(BASE_LAYERS)}") from None


def base_layer_versions(name: str) -> dict[str, str]:
    """Installed version of each base-layer dist present on this image."""
    out = {}
    for dist in base_layer_dists(name):
        try:
            out[dist] = importlib.metadata.version(dist)
        except importlib.metadata.PackageNotFoundError:
            pass
    return out


def host_site_packages() -> list[str]:
    """The runtime image's site-packages dirs (the physical base layer)."""
    paths = list(site.getsitepackages()) if hasattr(site, "getsitepackages") else []
    # fall back to deriving from a known stdlib-external module
    if not paths:
        import numpy

        paths = [str(Path(numpy.__file__).parent.parent)]
    return [p for p in paths if Path(p).is_dir()]


def runtime_sys_path(bundle_site: Path, base_layer: str) -> list[str]:
    """sys.path layering for the serve runtime: bundle delta first, then the
    base layer (host site-packages), then the stdlib already on sys.path."""
    path = [str(bundle_site)]
    if base_layer != "none":
        path.extend(host_site_packages())
    return path


def materialize_base_site(layer: str, dest: Path) -> Path:
    """Build a site dir containing *exactly* the base layer, as symlinks into
    the host env. Used by the build smoke so a base-layer recipe is tested
    against the declared layer contents, not the whole host site-packages
    (which would mask missing vendored files)."""
    import importlib.metadata as md

    dest = Path(dest)
    dest.mkdir(parents=True, exist_ok=True)
    for dist_name in base_layer_dists(layer):
        try:
            dist = md.distribution(dist_name)
        except md.PackageNotFoundError:
            continue
        tops: set[str] = set()
        for f in dist.files or []:
            first = Path(str(f)).parts[0] if Path(str(f)).parts else ""
            if first and first != "..":
                tops.add(first)
        for top in tops:
            src = Path(dist.locate_file(top))
            link = dest / top
            if src.exists() and not link.exists():
                link.symlink_to(src)
    return dest


def check_skew(manifest_versions: dict[str, str], layer: str) -> dict[str, tuple[str, str]]:
    """Compare bundle-recorded base-layer versions with the live image.
    Returns {dist: (built_against, live)} for mismatches."""
    live = base_layer_versions(layer)
    return {
        dist: (want, live.get(dist, "<absent>"))
        for dist, want in manifest_versions.items()
        if live.get(dist) != want
    }
