"""Serve runtime: the rebuild's #1 new call stack (SURVEY.md §4 E).

The reference stops at producing a zip for Lambda; the Lambda runtime that
boots it defines the cold-start/latency metrics. Here that runtime is a
framework component: bundle loader (sys.path layering over the base layer,
compilation-cache attach), handler protocol, warmup, HTTP serve loop with
structured metrics, and a local deploy target that stands in for the
TPU-serverless control plane.
"""

from lambdipy_tpu.runtime.loader import BootReport, load_bundle
from lambdipy_tpu.runtime.metrics import LatencyStats

__all__ = ["BootReport", "LatencyStats", "load_bundle"]
