"""Driver benchmark: flagship serving latency on the real chip.

Measures ResNet-50 bf16 batch-1 forward p50 (the BASELINE.json north-star
metric: <15 ms p50 on v5e-1) and prints ONE JSON line; ``vs_baseline`` is
the speedup vs the 15 ms target (>1 = beating it).

Hardened against the wedge that ate round 1 (rc=124 with no diagnosis,
then green on identical code in round 2): the measurement is a STAGED
probe — device enumerate -> 1k x 1k bf16 matmul -> ResNet bench — each
stage a separate subprocess with its own short timeout, so a TPU-tunnel
wedge is caught in minutes, attributed to the exact stage, and recorded
in the output JSON instead of a bare timeout. Compiles go through a
persistent compilation cache shared across attempts, so a killed first
attempt's completed compiles are not repaid on the retry. If every TPU
stage fails, the orchestrator falls back to CPU so the driver always
gets a valid JSON line, with ``platform`` recording what was measured.

Fault injection for tests: LAMBDIPY_BENCH_WEDGE=<stage> makes that stage
hang, proving the per-stage timeout + fallback machinery end to end.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import threading
import time

BASELINE_P50_MS = 15.0  # BASELINE.json north star for ResNet-50 on v5e-1
STAGES = ("devices", "matmul", "model")


def _stage_timeout(stage: str, platform: str) -> float:
    if stage == "model":
        default = "1500" if platform != "cpu" else "600"
        return float(os.environ.get("LAMBDIPY_BENCH_TIMEOUT", default))
    if stage == "decode":
        # compiles a full (small) Llama serve program — a real model
        # compile, not a probe; remote-compile transports need headroom
        return float(os.environ.get("LAMBDIPY_BENCH_DECODE_TIMEOUT", "900"))
    if stage == "decode8b":
        # 8 GB weight upload + a 32-layer program compile
        return float(os.environ.get("LAMBDIPY_BENCH_8B_TIMEOUT", "1500"))
    if stage == "devices":
        # the first probe is pure device enumeration (no model compile):
        # a wedged transport deserves a SHORT leash here, because this
        # stage is where every run of a dead tunnel burns its wait
        # (BENCH_r04/r05 paid 240 s per invocation before the fallback)
        return float(os.environ.get(
            "LAMBDIPY_DEVICE_PROBE_TIMEOUT_S",
            os.environ.get("LAMBDIPY_BENCH_PROBE_TIMEOUT", "60")))
    # probes only pay interpreter+PJRT init (~10 s) plus one small compile
    return float(os.environ.get("LAMBDIPY_BENCH_PROBE_TIMEOUT", "240"))


def _wedge_verdict_path() -> str:
    cache_dir = os.environ.get(
        "LAMBDIPY_BENCH_CACHE",
        os.path.expanduser("~/.lambdipy-tpu/cache/bench-compile"))
    return os.path.join(cache_dir, "device-wedge.json")


def _read_cached_wedge() -> str | None:
    """A still-fresh wedge verdict recorded by a previous bench
    invocation, or None. Repeated bench runs against a dead transport
    skip the device attempt instead of re-burning the probe timeout
    each time; LAMBDIPY_BENCH_WEDGE_TTL (seconds, default 600, 0
    disables) bounds how long a verdict is trusted."""
    ttl = float(os.environ.get("LAMBDIPY_BENCH_WEDGE_TTL", "600"))
    if ttl <= 0:
        return None
    try:
        with open(_wedge_verdict_path()) as f:
            rec = json.load(f)
        age = time.time() - float(rec["at"])
        if 0 <= age < ttl:
            return f"{rec['error']} [cached verdict, {age:.0f}s old]"
    except Exception:  # noqa: BLE001 — missing/corrupt cache = no verdict
        return None
    return None


def _write_wedge_verdict(error: str) -> None:
    try:
        path = _wedge_verdict_path()
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path, "w") as f:
            json.dump({"error": error, "at": time.time()}, f)
    except Exception:  # noqa: BLE001 — the cache is an optimization
        pass


def _maybe_wedge(stage: str) -> None:
    """Fault injection: LAMBDIPY_BENCH_WEDGE='<stage>' hangs that stage in
    every attempt; '<attempt>.<stage>' (e.g. 'device.devices') hangs it in
    one attempt only, so tests can prove the timeout->fallback path."""
    spec = os.environ.get("LAMBDIPY_BENCH_WEDGE", "")
    attempt = os.environ.get("LAMBDIPY_BENCH_ATTEMPT", "")
    if spec and spec in (stage, f"{attempt}.{stage}"):
        time.sleep(3600)


def _enable_compile_cache() -> None:
    """Persistent compilation cache shared across attempts/stages, so a
    killed attempt's completed compiles survive to the retry."""
    import jax

    cache_dir = os.environ.get(
        "LAMBDIPY_BENCH_CACHE",
        os.path.expanduser("~/.lambdipy-tpu/cache/bench-compile"))
    try:
        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    except Exception as e:  # cache is an optimization, never a failure
        print(f"compile cache unavailable: {e}", file=sys.stderr)


def _init_jax():
    t0 = time.monotonic()
    import jax

    if os.environ.get("LAMBDIPY_PLATFORM"):
        jax.config.update("jax_platforms", os.environ["LAMBDIPY_PLATFORM"])
    _enable_compile_cache()
    devices = jax.devices()
    return jax, devices, time.monotonic() - t0


def _stage_devices() -> int:
    _maybe_wedge("devices")
    _, devices, init_s = _init_jax()
    print(json.dumps({"platform": devices[0].platform,
                      "n_devices": len(devices),
                      "init_s": round(init_s, 2)}))
    return 0


def _stage_matmul() -> int:
    _maybe_wedge("matmul")
    jax, devices, init_s = _init_jax()
    import jax.numpy as jnp

    a = jnp.ones((1024, 1024), jnp.bfloat16)
    f = jax.jit(lambda x: x @ x)
    t0 = time.monotonic()
    jax.block_until_ready(f(a))
    compile_s = time.monotonic() - t0
    t0 = time.monotonic()
    jax.block_until_ready(f(a))
    print(json.dumps({"platform": devices[0].platform,
                      "init_s": round(init_s, 2),
                      "matmul_compile_s": round(compile_s, 2),
                      "matmul_ms": round((time.monotonic() - t0) * 1e3, 3)}))
    return 0


def _measure_rtt_ms(jax, jnp) -> float:
    """Per-fetch transport floor: median ms to fetch a FRESH tiny device
    result host-side (one network RTT through a remote PJRT tunnel, ~0 on
    attached hardware)."""
    import statistics

    f = jax.jit(lambda x: (x * 2).sum())
    xd = jax.device_put(jnp.ones((8, 8), jnp.float32))
    float(f(xd))
    return statistics.median([_timed(lambda: float(f(xd)))
                              for _ in range(10)])


def _stage_model() -> int:
    """Headline: host-observed EXECUTION p50, net of the transport floor.

    On this image's remote PJRT tunnel ``block_until_ready`` returns at
    submission (~0.03 ms) without waiting for remote completion — only a
    host fetch observes the device finish. So the headline times
    ``jax.device_get`` of the output and subtracts the independently
    measured per-fetch RTT floor; submission latency stays published as
    ``submit_p50_ms``. On attached hardware the two converge (rtt ~0 and
    block_until_ready is truthful). VERDICT r3 weak #1.
    """
    import statistics

    _maybe_wedge("model")
    jax, devices, init_s = _init_jax()
    import jax.numpy as jnp

    from lambdipy_tpu.models import registry
    from lambdipy_tpu.utils import roofline

    platform = devices[0].platform
    model = os.environ.get("LAMBDIPY_BENCH_MODEL", "resnet50")
    adapter = registry.get(model).build(
        dtype="bfloat16" if model == "resnet50" else "float32")
    params = adapter.init_params(seed=0, batch_size=1)
    (x,) = adapter.example_batch(1)
    fwd = jax.jit(adapter.forward)

    t1 = time.monotonic()
    jax.device_get(fwd(params, x))
    compile_s = time.monotonic() - t1

    for _ in range(5):
        jax.device_get(fwd(params, x))
    rtt = _measure_rtt_ms(jax, jnp) if platform != "cpu" else 0.0
    iters = 50 if platform != "cpu" else 10
    exec_times = [_timed(lambda: jax.device_get(fwd(params, x)))
                  for _ in range(iters)]
    submit_times = [_timed(lambda: jax.block_until_ready(fwd(params, x)))
                    for _ in range(iters)]
    p50 = max(0.001, statistics.median(exec_times) - rtt)

    record = {
        "metric": f"{model}_b1_fwd_p50",
        "value": round(p50, 3),
        "unit": "ms",
        "vs_baseline": round(BASELINE_P50_MS / p50, 3),
        "methodology": "host-observed execution time (device_get) minus "
                       "measured per-fetch transport RTT floor",
        "submit_p50_ms": round(statistics.median(submit_times), 3),
        "fetch_rtt_ms": round(rtt, 2),
        "platform": platform,
        "n_devices": len(devices),
        "init_s": round(init_s, 2),
        "first_compile_s": round(compile_s, 2),
    }
    if model == "resnet50":
        cost = roofline.resnet50_cost(batch=1)
        record.update({f"model_{k}": v
                       for k, v in cost.utilization(p50 / 1e3).items()})
    print(json.dumps(record))
    return 0


def _stage_decode() -> int:
    """Best-effort secondary metric: int8 Llama decode throughput through
    the compile-once server (the config-5 exemplar dims), net of the
    transport's per-fetch round trip. Failure of this stage never
    degrades the headline metric — the orchestrator merges its keys only
    when it succeeds."""
    import statistics

    _maybe_wedge("decode")
    jax, devices, init_s = _init_jax()
    import jax.numpy as jnp

    from lambdipy_tpu.models import registry
    from lambdipy_tpu.utils import roofline

    n_new = 64
    adapter = registry.get("llama3-8b").build(
        dtype="bfloat16", quant="int8",
        extra={"vocab_size": 16384, "hidden": 768, "layers": 6,
               "heads": 12, "kv_heads": 4, "mlp": 2048, "max_len": 1024})
    params = jax.device_put(adapter.init_params(seed=0))
    server = adapter.make_server(params)
    prompt = [1, 2, 3, 4, 5, 6, 7, 8]
    server.generate(prompt, max_new_tokens=n_new)  # compile + warm

    # transport floor subtracted so tok/s measures the decode
    rtt = _measure_rtt_ms(jax, jnp)
    times = [_timed(lambda: server.generate(prompt, max_new_tokens=n_new))
             for _ in range(10)]
    net_ms = max(0.1, statistics.median(times) - rtt)
    # per-decoded-token utilization at the mean cache length of the run
    cost = roofline.llama_decode_step_cost(
        adapter.config, batch=1, cache_len=len(prompt) + n_new // 2)
    record = {
        "decode_tok_s": round(n_new / (net_ms / 1e3), 1),
        "decode_net_ms": round(net_ms, 2),
        "decode_rtt_ms": round(rtt, 2),
        "decode_n_new": n_new,
        "decode_dims": f"{adapter.config.hidden}x{adapter.config.layers}"
                       f"x{adapter.config.vocab_size}",
    }
    record.update({f"decode_{k}": v
                   for k, v in cost.utilization(net_ms / n_new / 1e3).items()
                   if k in ("mfu", "hbm_util", "roofline_ms")})
    print(json.dumps(record))
    return 0


def _stage_decode8b() -> int:
    """REAL-dims secondary metric: Llama-3-8B int8 (4096x32x128256) batch-8
    decode through LlamaServer, with HBM-utilization accounting. Runs only
    when the random-init 8B flatpack is already cached (scripts/
    measure_8b.py builds it once, ~6 min) or LAMBDIPY_BENCH_8B_GEN=1
    forces generation; failure or absence never degrades the headline."""
    import importlib.util

    _maybe_wedge("decode8b")
    spec = importlib.util.spec_from_file_location(
        "measure_8b",
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     "scripts", "measure_8b.py"))
    m8b = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(m8b)
    if not m8b.params_path().is_file() and \
            os.environ.get("LAMBDIPY_BENCH_8B_GEN") != "1":
        print(json.dumps({"decode8b": "skipped: no cached 8B params "
                          "(run scripts/measure_8b.py once)"}))
        return 0
    rec = m8b.measure(batches=(8,), n_new=64, do_prefill=False)
    print(json.dumps({
        "decode8b_tok_s": rec["b8_decode_tok_s"],
        "decode8b_hbm_util": rec["b8_decode_hbm_util"],
        "decode8b_roofline_tok_s": rec["b8_roofline_tok_s"],
        "decode8b_dims": rec["dims"],
        "decode8b_batch": 8,
        "decode8b_weight_upload_s": rec["weight_upload_s"],
    }))
    return 0


def _shared_prefix_rows(rng, *, n_requests: int, prefix_len: int,
                        suffix_len: int, vocab: int) -> list:
    """The --shared-prefix workload generator: ``n_requests`` prompts
    sharing one random ``prefix_len``-token prefix, each with a distinct
    random suffix. Also the --fleet workload's per-group generator."""
    shared = rng.integers(1, vocab, prefix_len).tolist()
    return [shared + rng.integers(1, vocab, suffix_len).tolist()
            for _ in range(n_requests)]


def shared_prefix_record(*, n_requests: int = 8, prefix_len: int = 512,
                         suffix_len: int = 16, n_new: int = 16,
                         block: int = 64, extra: dict | None = None) -> dict:
    """Shared-prefix serving workload: ``n_requests`` prompts sharing one
    ``prefix_len``-token prefix (distinct suffixes), run with the
    automatic prefix cache OFF (full-prompt prefill per request) and ON
    (radix-matched, suffix-only continuation). Reports measured wall /
    tok/s / time-to-first-token for both, asserts TOKEN PARITY between
    the two runs, and attaches the roofline model's analytic prefill
    FLOP counts — the headline is ``prefill_flop_ratio``: how many times
    fewer prefill FLOPs the cache-on run executes. CPU-runnable at the
    default tiny dims (the parity + ratio claims are platform-free)."""
    import statistics

    import numpy as np

    import jax

    from lambdipy_tpu.models import registry
    from lambdipy_tpu.runtime.prefixstore import PrefixStore
    from lambdipy_tpu.utils import roofline

    dims = {"vocab_size": 2048, "hidden": 128, "layers": 2, "heads": 4,
            "kv_heads": 2, "mlp": 256,
            "max_len": max(1024, 2 * (prefix_len + suffix_len + n_new))}
    dims.update(extra or {})
    adapter = registry.get("llama3-8b").build(dtype="float32", extra=dims)
    cfg = adapter.config
    params = jax.device_put(adapter.init_params(seed=0))

    rng = np.random.default_rng(0)
    rows = _shared_prefix_rows(rng, n_requests=n_requests,
                               prefix_len=prefix_len,
                               suffix_len=suffix_len,
                               vocab=cfg.vocab_size)
    # warm traffic: same shapes, disjoint tokens — compiles every program
    # both paths need without seeding the store with the workload prefix
    warm_row = rng.integers(1, cfg.vocab_size,
                            prefix_len + suffix_len).tolist()

    def ttft(server, row, prefix=None):
        t0 = time.monotonic()
        next(iter(server.generate_stream(row, max_new_tokens=n_new,
                                         segment=4, prefix=prefix)))
        return (time.monotonic() - t0) * 1e3

    # -- cache OFF: every request prefills its whole prompt ------------------
    server_off = adapter.make_server(params)
    server_off.generate(warm_row, max_new_tokens=n_new)
    ttft(server_off, warm_row)
    t0 = time.monotonic()
    off_out = [server_off.generate(r, max_new_tokens=n_new) for r in rows]
    off_s = time.monotonic() - t0
    off_ttft = [ttft(server_off, r) for r in rows]

    # -- cache ON: radix match, suffix-only continuation ---------------------
    server_on = adapter.make_server(params)
    store = PrefixStore(server_on, block=block, budget_mb=64)
    m_warm = store.route(warm_row)
    server_on.generate(warm_row[m_warm:], prefix=warm_row[:m_warm],
                       max_new_tokens=n_new)
    ttft(server_on, warm_row[m_warm:], prefix=warm_row[:m_warm])

    def on_generate(row):
        m = store.route(row)
        if m <= 0:
            return server_on.generate(row, max_new_tokens=n_new)
        return server_on.generate(row[m:], prefix=row[:m],
                                  max_new_tokens=n_new)

    t0 = time.monotonic()
    on_out = [on_generate(row) for row in rows]
    on_s = time.monotonic() - t0

    def on_ttft(row):
        m = store.match_len(row)
        t0 = time.monotonic()
        next(iter(server_on.generate_stream(
            row[m:], max_new_tokens=n_new, segment=4,
            prefix=row[:m] if m else None)))
        return (time.monotonic() - t0) * 1e3

    on_ttfts = [on_ttft(r) for r in rows]

    parity = all(np.array_equal(a, b) for a, b in zip(off_out, on_out))
    if not parity:
        # the docstring's promise is load-bearing: a parity regression
        # must fail the bench loudly (nonzero rc), not ride out as a
        # field only pytest wrappers read
        raise AssertionError("shared-prefix parity broke: cache-on "
                             "tokens != cache-off tokens")
    matched = store.match_len(rows[0])
    # analytic prefill FLOPs: OFF pays the full prompt per request; ON
    # pays ONE cold radix walk (= one full prefill of the shared blocks)
    # plus a suffix-only continuation per request
    flops_off = n_requests * roofline.llama_prefill_cost(
        cfg, batch=1, seq_len=len(rows[0])).flops
    flops_on = roofline.llama_prefill_cost(
        cfg, batch=1, seq_len=matched).flops
    for row in rows:
        m = store.match_len(row)
        flops_on += roofline.llama_prefix_continue_cost(
            cfg, suffix_len=len(row) - m, prefix_len=m).flops
    total_new = n_requests * n_new
    return {
        "mode": "shared_prefix",
        "platform": jax.devices()[0].platform,
        "n_requests": n_requests,
        "prefix_len": prefix_len,
        "suffix_len": suffix_len,
        "n_new": n_new,
        "block": store.block,
        "parity": parity,
        "off_tok_s": round(total_new / off_s, 1),
        "on_tok_s": round(total_new / on_s, 1),
        "speedup": round(off_s / on_s, 3),
        "off_ttft_p50_ms": round(statistics.median(off_ttft), 2),
        "on_ttft_p50_ms": round(statistics.median(on_ttfts), 2),
        "prefill_flops_off": flops_off,
        "prefill_flops_on": flops_on,
        "prefill_flop_ratio": round(flops_off / flops_on, 2),
        "prefix_cache": store.stats(),
    }


def _build_fleet_bundle(tmp, *, n_new: int, block: int,
                        name: str = "fleet-bench"):
    """Assemble the tiny llama bundle the fleet sweeps serve (prefix
    cache on, deterministic init params so every replica is bitwise the
    same server)."""
    from lambdipy_tpu.buildengine import build_recipe
    from lambdipy_tpu.bundle import assemble_bundle
    from lambdipy_tpu.recipes.schema import load_recipe_dict

    doc = {
        "schema": 1, "name": name, "version": "0.1",
        "device": "any", "base_layer": "jax-tpu", "requires": [],
        "payload": {
            "model": "llama-tiny",
            "handler": "lambdipy_tpu.runtime.handlers:generate_handler",
            "params": "init", "dtype": "float32",
            "extra": {"max_new_tokens": str(n_new), "serve_aot": "0",
                      "warm_group_prefill": "0",
                      "prefix_cache_mb": "64",
                      "prefix_block": str(block)},
        },
    }
    result = build_recipe(load_recipe_dict(doc), tmp / "work",
                          run_smoke=False)
    bundle = tmp / "bundle"
    assemble_bundle(result, bundle, with_payload=True)
    return bundle


def _build_disagg_bundle(tmp, *, n_new: int, block: int,
                         name: str = "disagg-bench"):
    """The tiny llama bundle the disaggregation sweep serves: prefix
    cache on (the ship surface rides it), CONTINUOUS batching (the
    decode-depth story), deterministic init params so every replica is
    bitwise the same server."""
    from lambdipy_tpu.buildengine import build_recipe
    from lambdipy_tpu.bundle import assemble_bundle
    from lambdipy_tpu.recipes.schema import load_recipe_dict

    doc = {
        "schema": 1, "name": name, "version": "0.1",
        "device": "any", "base_layer": "jax-tpu", "requires": [],
        "payload": {
            "model": "llama-tiny",
            "handler": "lambdipy_tpu.runtime.handlers:generate_handler",
            "params": "init", "dtype": "float32",
            # a 512-token window + wider hidden than the test-tiny
            # defaults: the isolation claim needs prefill that COSTS
            # something relative to a decode step (a 256-token cold
            # walk is ~8 chunked forwards over a growing context),
            # which the 128-token test config cannot express
            # sched_max_concurrency=1 serializes each replica like the
            # one accelerator it stands in for: a request occupies the
            # replica for its service time, so prefill occupancy and
            # decode occupancy genuinely contend — the mechanism the
            # phase split exists to separate (on a shared-CPU box,
            # concurrent slots would hide occupancy behind the OS
            # scheduler and the isolation claim would measure nothing)
            "extra": {"max_new_tokens": str(n_new), "serve_aot": "0",
                      "warm_group_prefill": "0",
                      "prefix_cache_mb": "64",
                      "prefix_block": str(block),
                      "max_len": "512", "hidden": "128",
                      "sched_max_concurrency": "1",
                      "batch_mode": "continuous",
                      "batch_max": "4", "batch_segment": "8"},
        },
    }
    result = build_recipe(load_recipe_dict(doc), tmp / "work",
                          run_smoke=False)
    bundle = tmp / "bundle"
    assemble_bundle(result, bundle, with_payload=True)
    return bundle


def _spawn_replica_proc(bundle, *, env_extra=None, tag="r",
                        ready_timeout=300.0, port=0):
    """Boot one bundle server as a SUBPROCESS (own jax client, own
    XLA threadpool — the disaggregation claim is about isolating
    replica workloads, which in-process replicas sharing one device
    client cannot honestly show). Returns (proc, url, stderr_path).
    ``port`` pins the listen port — the sessions sweep respawns a
    SIGKILLed replica at its old URL so the pool readmits it."""
    import subprocess
    import tempfile

    here = os.path.dirname(os.path.abspath(__file__))
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = os.pathsep.join(
        [here] + [p for p in env.get("PYTHONPATH", "").split(os.pathsep)
                  if p])
    env.update(env_extra or {})
    errf = tempfile.NamedTemporaryFile(
        prefix=f"lambdipy-disagg-{tag}-", suffix=".stderr", delete=False)
    proc = subprocess.Popen(
        [sys.executable, "-m", "lambdipy_tpu.runtime.server",
         str(bundle)] + ([str(port)] if port else []),
        stdout=subprocess.PIPE, stderr=errf, text=True, env=env)
    ready: dict = {}

    def _reader():
        for line in proc.stdout:
            line = line.strip()
            if not line:
                continue
            try:
                msg = json.loads(line)
            except json.JSONDecodeError:
                continue
            if msg.get("ready"):
                ready.update(msg)
                return

    t = threading.Thread(target=_reader, daemon=True)
    t.start()
    t.join(timeout=ready_timeout)
    if not ready:
        proc.kill()
        tail = ""
        try:
            with open(errf.name) as f:
                tail = f.read()[-800:]
        except OSError:
            pass
        raise RuntimeError(
            f"replica {tag} never printed its ready line: {tail}")
    return proc, f"http://127.0.0.1:{ready['port']}", errf.name


def disagg_record(*, block: int = 64, prefix_len: int = 64,
                  suffix_len: int = 8, n_new: int = 24,
                  parity_requests: int = 6, decode_window_s: float = 6.0,
                  decode_new: int = 64, burst_len: int = 449,
                  burst_requests: int = 8, walk_ms: float = 90.0,
                  min_speedup: float = 1.2) -> dict:
    """Disaggregated prefill/decode sweep (CPU-runnable, SUBPROCESS
    replicas). Three claims, each a hard assert:

    1. PARITY — a split fleet (1 decode-class + 1 prefill-class replica
       behind the phase-split router) answers BITWISE what one replica
       answers directly: greedy + seeded-sampled, dense + paged KV, with
       real ships observed (router decode_dispatches > 0; on the paged
       fleet the decode replica's imports are zero-copy page inserts).
    2. ISOLATION — under a concurrent cold-prefill burst, the split
       fleet's decode throughput is >= ``min_speedup`` x the MIXED fleet
       of the same two replicas: prefill bursts land on the prefill
       class (the export IS the prefill), so the decode replica's batch
       keeps streaming instead of stalling behind walk prefills.
    3. DEGRADATION — with every ship failing (injected ``kv_ship``
       fault), the whole burst still completes bitwise with ZERO
       client-visible errors: a dead ship path costs mixed-mode local
       prefill, never a request (the --chaos-fleet bar).
    """
    import tempfile
    import urllib.request
    from concurrent.futures import ThreadPoolExecutor
    from pathlib import Path

    import numpy as np

    from lambdipy_tpu.fleet import DECODE, MIXED, PREFILL, FleetRouter, \
        ReplicaPool
    from lambdipy_tpu.runtime.faults import FaultPlan

    tmp = Path(tempfile.mkdtemp(prefix="lambdipy-disagg-bench-"))
    bundle = _build_disagg_bundle(tmp, n_new=n_new, block=block)
    rng = np.random.default_rng(0)

    def post(base, path, payload, timeout=300):
        req = urllib.request.Request(
            f"{base}{path}", data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"}, method="POST")
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return json.loads(resp.read())

    def completion(base, row, *, max_tokens, **kw):
        out = post(base, "/v1/completions",
                   {"prompt": [int(t) for t in row],
                    "max_tokens": max_tokens,
                    "temperature": kw.get("temperature", 0),
                    **({"seed": kw["seed"]} if "seed" in kw else {}),
                    **({"top_p": kw["top_p"]} if "top_p" in kw else {})})
        return out["choices"][0]["tokens"]

    def metrics(base):
        with urllib.request.urlopen(f"{base}/metrics",
                                    timeout=60) as resp:
            return json.loads(resp.read())

    def boot_pair(env_extra=None, tag=""):
        out = [None, None]
        errs: list = []

        def boot(i, t):
            try:
                out[i] = _spawn_replica_proc(bundle, env_extra=env_extra,
                                             tag=t)
            except Exception as e:  # noqa: BLE001 — re-raised below
                errs.append(e)

        threads = [threading.Thread(target=boot, args=(i, f"{tag}{i}"))
                   for i in range(2)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        if errs:
            for rec in out:
                if rec is not None:
                    rec[0].kill()
            raise errs[0]
        return out

    def split_router(pool_specs, *, faults=None):
        pool = ReplicaPool(probe_interval=0.5, fail_threshold=2,
                           probe_timeout=10.0)
        for name, url, role in pool_specs:
            pool.attach(name, url, role=role)
        pool.probe_all()
        pool.start()
        router = FleetRouter(pool, affinity_on=True, block=block,
                             max_retries=2, request_timeout=300,
                             faults=faults or FaultPlan.empty())
        return router.start_background(), pool

    result: dict = {"mode": "disagg", "block": block, "n_new": n_new}

    # ---- claim 1: bitwise parity, dense + paged -----------------------------
    for paged in (False, True):
        label = "paged" if paged else "dense"
        # synthetic prefill device time (the PR-5 synthetic-RTT idiom):
        # every cold-walk chunk pays walk_ms through the deterministic
        # prefix_walk fault site, on EVERY replica identically. The
        # bench box is a single shared CPU, where real prefill FLOPs
        # are zero-sum across replica processes and isolation would be
        # unmeasurable; modeled device time occupies only the replica
        # that runs the prefill — which is exactly the resource the
        # phase split moves. Exports pay it too (the export IS the
        # prefill), so the split fleet gets no free lunch.
        env_extra = {"LAMBDIPY_FAULT":
                     f"prefix_walk:delay@ms={walk_ms:g},n=inf"}
        if paged:
            # arena sized to the dense engine's footprint plus headroom
            # for store-owned imported pages (imports alloc strictly)
            env_extra.update({"LAMBDIPY_KV_PAGED": "1",
                              "LAMBDIPY_KV_PAGES": "96"})
        (pd, dec_url, _), (pp, pre_url, _) = boot_pair(env_extra, label)
        try:
            groups = [
                _shared_prefix_rows(rng, n_requests=parity_requests,
                                    prefix_len=prefix_len,
                                    suffix_len=suffix_len, vocab=500)
                for _ in range(2)]
            rows = [r for g in groups for r in g]
            kws = [{}, {"temperature": 0.9, "seed": 7, "top_p": 0.9}]
            # reference = the PREFILL replica hit directly (identical
            # init params -> bitwise-identical servers); asking it also
            # pre-warms its radix store, which is exactly the state the
            # export leg serves from
            refs = {}
            for kw in kws:
                for row in rows:
                    refs[(tuple(row), tuple(sorted(kw)))] = completion(
                        pre_url, row, max_tokens=n_new, **kw)
            router, pool = split_router(
                [("dec", dec_url, DECODE), ("pre", pre_url, PREFILL)])
            base = f"http://127.0.0.1:{router.port}"
            try:
                mismatches = []

                def one(args):
                    row, kw = args
                    got = completion(base, row, max_tokens=n_new, **kw)
                    if got != refs[(tuple(row), tuple(sorted(kw)))]:
                        mismatches.append((row[:4], kw))

                jobs = [(row, kw) for kw in kws for row in rows]
                with ThreadPoolExecutor(max_workers=4) as ex:
                    list(ex.map(one, jobs))
                if mismatches:
                    raise AssertionError(
                        f"disagg {label} parity broke: split-fleet "
                        f"tokens != direct for {mismatches[:3]}")
                rep = router.disagg.report()
                if rep["decode_dispatches"] < 1:
                    raise AssertionError(
                        f"disagg {label}: no ship ever landed "
                        f"({rep}) — the parity run tested nothing")
                dec_m = metrics(dec_url)
                ship = dec_m["handler"]["batching"]["disagg"]
                if ship["imports"] < 1:
                    raise AssertionError(
                        f"disagg {label}: decode replica saw no "
                        f"imports: {ship}")
                if paged and ship["imports_zero_copy"] < 1:
                    raise AssertionError(
                        f"disagg paged: imports were not zero-copy "
                        f"page inserts: {ship}")
                result[f"parity_{label}"] = {
                    "requests": len(jobs),
                    "ships": rep["ships"],
                    "ship_bytes_ewma": rep["ship_bytes_ewma"],
                    "ship_ms_ewma": rep["ship_ms_ewma"],
                    "decode_imports": ship["imports"],
                    "zero_copy": ship["imports_zero_copy"],
                    "fallbacks": rep["fallbacks"],
                }
            finally:
                router.stop()
                pool.close()
            if not paged:
                # ---- claims 2 + 3 ride the dense pair -------------------
                result["throughput"] = _disagg_throughput(
                    dec_url, pre_url, block=block,
                    decode_window_s=decode_window_s,
                    decode_new=decode_new, burst_len=burst_len,
                    min_speedup=min_speedup, split_router=split_router,
                    completion=completion, rng=rng)
                result["ship_failure"] = _disagg_ship_failure(
                    dec_url, pre_url, block=block, n_new=4,
                    burst_len=burst_len, burst_requests=burst_requests,
                    split_router=split_router, completion=completion,
                    rng=rng)
        finally:
            for p in (pd, pp):
                p.kill()
    result["passed"] = True
    import jax

    result["platform"] = jax.devices()[0].platform
    return result


def _disagg_rows(rng, *, n, length, vocab=500):
    return [[int(t) for t in rng.integers(1, vocab, size=length)]
            for _ in range(n)]


def _disagg_throughput(dec_url, pre_url, *, block, decode_window_s,
                       decode_new, burst_len, min_speedup, split_router,
                       completion, rng, burst_interval_ms=500.0,
                       max_bursts=80):
    """Claim 2: decode tok/s under a concurrent cold-prefill burst,
    split fleet vs the SAME two replicas as a mixed fleet.

    Two load-generation rules keep the comparison honest and the gate
    stable on a shared CPU box:

    - The burst load is OPEN-LOOP: a scheduler fires one fresh cold
      prompt (distinct ~448-token prefix — every one ships) every
      ``burst_interval_ms`` for the whole window, regardless of how
      fast the fleet absorbs them. A closed loop would self-pace to
      each mode's own prefill latency and offer the slower fleet LESS
      load — exactly backwards for an isolation comparison. Every
      issued burst must complete (zero-loss bar) before the routers
      stop.
    - The decode stream runs for a FIXED WALL WINDOW
      (``decode_window_s``), not a fixed request count: tok/s is
      completed decode tokens over the actual window, so a few slow
      requests stretch the denominator instead of ending the
      measurement early.
    """
    import numpy as np
    from concurrent.futures import ThreadPoolExecutor

    from lambdipy_tpu.fleet import DECODE, MIXED, PREFILL

    out = {}
    for mode, roles in (("mixed", (MIXED, MIXED)),
                        ("split", (DECODE, PREFILL))):
        router, pool = split_router(
            [("dec", dec_url, roles[0]), ("pre", pre_url, roles[1])])
        base = f"http://127.0.0.1:{router.port}"
        try:
            # fresh token namespaces per mode: no cross-mode cache
            # warmth (each mode pays its own cold prefix insert)
            prefix = _disagg_rows(rng, n=1, length=block)[0]
            dec_rows = [prefix + _disagg_rows(rng, n=1, length=8)[0]
                        for _ in range(64)]
            # off-the-clock warm: the decode prefix lands in its
            # affinity target's radix store, and one burst-shaped
            # request compiles the chunked-prefill + suffix-1 joiner
            # programs in BOTH modes so neither measurement pays a
            # first-use compile
            completion(base, dec_rows[0], max_tokens=decode_new)
            completion(base, _disagg_rows(rng, n=1,
                                          length=burst_len)[0],
                       max_tokens=1)
            stop = threading.Event()
            done = [0]
            burst_threads: list = []
            burst_errors: list = []

            def burst_once(row):
                try:
                    completion(base, row, max_tokens=1)
                    done[0] += 1
                except Exception as e:  # noqa: BLE001 — a lost burst
                    burst_errors.append(f"{type(e).__name__}: {e}")

            def burst_scheduler():
                # rows are drawn HERE (one thread) so the shared rng
                # never races; each burst gets its own worker thread
                while not stop.is_set() and \
                        len(burst_threads) < max_bursts:
                    row = _disagg_rows(rng, n=1, length=burst_len)[0]
                    t = threading.Thread(target=burst_once, args=(row,),
                                         daemon=True)
                    t.start()
                    burst_threads.append(t)
                    if stop.wait(burst_interval_ms / 1e3):
                        return

            tokens = [0]
            tok_lock = threading.Lock()
            t0 = time.monotonic()

            def decode_worker(widx):
                i = widx
                while time.monotonic() - t0 < decode_window_s:
                    completion(base, dec_rows[i % len(dec_rows)],
                               max_tokens=decode_new)
                    with tok_lock:
                        tokens[0] += decode_new
                    i += 2

            sched = threading.Thread(target=burst_scheduler, daemon=True)
            sched.start()
            with ThreadPoolExecutor(max_workers=2) as ex:
                list(ex.map(decode_worker, (0, 1)))
            wall = time.monotonic() - t0
            stop.set()
            sched.join(timeout=10)
            for t in burst_threads:  # zero-loss: every burst completes
                t.join(timeout=120)
            if burst_errors or any(t.is_alive() for t in burst_threads):
                raise AssertionError(
                    f"disagg throughput ({mode}): burst requests were "
                    f"lost or wedged: {burst_errors[:3]}")
            out[mode] = {
                "decode_tok_s": round(tokens[0] / wall, 1),
                "decode_tokens": tokens[0],
                "wall_s": round(wall, 3),
                "bursts_issued": len(burst_threads),
                "bursts_done": done[0],
            }
            if roles[1] == PREFILL:
                out["split_disagg"] = {
                    k: router.disagg.report()[k]
                    for k in ("ships", "ship_skips", "fallbacks",
                              "ship_ms_ewma")}
        finally:
            router.stop()
            pool.close()
    ratio = out["split"]["decode_tok_s"] / max(
        1e-9, out["mixed"]["decode_tok_s"])
    out["decode_speedup"] = round(ratio, 3)
    out["min_speedup"] = min_speedup
    if ratio < min_speedup:
        raise AssertionError(
            f"disagg throughput: split-fleet decode tok/s under a "
            f"prefill burst is only {ratio:.2f}x the mixed fleet "
            f"(gate {min_speedup}x): {out}")
    return out


def _disagg_ship_failure(dec_url, pre_url, *, block, n_new, burst_len,
                         burst_requests, split_router, completion, rng):
    """Claim 3: every ship fails (injected router-side kv_ship fault),
    the burst still completes bitwise with zero client-visible errors —
    phase-split degradation is mixed-mode, never loss."""
    from concurrent.futures import ThreadPoolExecutor

    from lambdipy_tpu.fleet import DECODE, PREFILL
    from lambdipy_tpu.runtime.faults import FaultPlan

    rows = _disagg_rows(rng, n=burst_requests, length=burst_len)
    # bitwise reference from the prefill replica hit directly (bitwise-
    # identical server; the faulted fleet must reproduce these exactly)
    refs = [completion(pre_url, row, max_tokens=n_new) for row in rows]
    plan = FaultPlan.from_spec("kv_ship:exception@seg=1,n=inf")
    router, pool = split_router(
        [("dec", dec_url, DECODE), ("pre", pre_url, PREFILL)],
        faults=plan)
    base = f"http://127.0.0.1:{router.port}"
    try:
        errors: list = []

        def one(i):
            try:
                got = completion(base, rows[i], max_tokens=n_new)
                if got != refs[i]:
                    errors.append(f"row {i}: tokens diverged")
            except Exception as e:  # noqa: BLE001 — any error fails
                errors.append(f"row {i}: {type(e).__name__}: {e}")

        with ThreadPoolExecutor(max_workers=4) as ex:
            list(ex.map(one, range(len(rows))))
        rep = router.disagg.report()
        if errors:
            raise AssertionError(
                f"disagg ship-failure: client-visible damage with "
                f"ships down: {errors[:3]}")
        if rep["fallbacks"].get("ship_fault", 0) < 1:
            raise AssertionError(
                f"disagg ship-failure: the injected fault never bit "
                f"({rep['fallbacks']}) — the case tested nothing")
        if rep["ships"] != 0:
            raise AssertionError(
                "disagg ship-failure: a ship landed despite the "
                "permanent fault")
        return {"requests": len(rows), "delivered": len(rows),
                "fallbacks": rep["fallbacks"], "parity": True}
    finally:
        router.stop()
        pool.close()


def _build_rtt_bundle(tmp, *, block: int, max_len: int,
                      name: str = "disagg-rtt-bench"):
    """The RTT sweep's bundle: prefill_chunk pinned to the prefix
    block, so every cold-walk chunk is ONE block and the export stream
    flushes one wire frame per block — the finest overlap granularity
    the store produces, which is what a per-chunk synthetic RTT
    measures."""
    from lambdipy_tpu.buildengine import build_recipe
    from lambdipy_tpu.bundle import assemble_bundle
    from lambdipy_tpu.recipes.schema import load_recipe_dict

    doc = {
        "schema": 1, "name": name, "version": "0.1",
        "device": "any", "base_layer": "jax-tpu", "requires": [],
        "payload": {
            "model": "llama-tiny",
            "handler": "lambdipy_tpu.runtime.handlers:generate_handler",
            "params": "init", "dtype": "float32",
            "extra": {"max_new_tokens": "4", "serve_aot": "0",
                      "warm_group_prefill": "0",
                      "prefix_cache_mb": "64",
                      "prefix_block": str(block),
                      "prefill_chunk": str(block),
                      "max_len": str(max_len), "hidden": "128",
                      "sched_max_concurrency": "1",
                      "batch_mode": "continuous",
                      "batch_max": "4", "batch_segment": "8"},
        },
    }
    result = build_recipe(load_recipe_dict(doc), tmp / "work",
                          run_smoke=False)
    bundle = tmp / "bundle"
    assemble_bundle(result, bundle, with_payload=True)
    return bundle


def disagg_rtt_record(*, block: int = 32, max_len: int = 1024,
                      chunk_ms: float = 66.0, walk_ms: float = 66.0,
                      requests: int = 3, max_ratio: float = 0.6,
                      ship_window: int = 4) -> dict:
    """Synthetic-RTT axis for the disaggregated ship (CPU-runnable,
    subprocess replicas): every relayed chunk pays ``chunk_ms`` through
    the deterministic ``kv_ship_chunk`` delay site (the wire), and
    every cold-walk chunk pays ``walk_ms`` through ``prefix_walk`` (the
    prefill device time) — the PR-5/PR-12 modeled-time idiom. Two hard
    gates:

    1. OVERLAP — cold-request TTFT through the PIPELINED ship must be
       <= ``max_ratio`` x the blocking (buffer-then-relay) ship's at
       the same per-chunk RTT: with prefill and wire both paying
       ~``chunk_ms`` per block, the blocking ship serializes them
       (2 x N x chunk_ms) while the pipelined ship hides the transfer
       under the remaining prefill (~N x chunk_ms) — the ROADMAP
       "66 ms-RTT transport would motivate an async/pipelined ship"
       remainder, measured.
    2. DEGRADATION — with every relayed chunk failing (permanent
       ``kv_ship_chunk`` exception), every request still answers
       BITWISE the direct reference with zero client-visible errors,
       and a repeated prefix re-ships (the aborted stream never marks
       the dedup LRU).
    """
    import statistics
    import tempfile
    import urllib.request
    from pathlib import Path

    import numpy as np

    from lambdipy_tpu.fleet import DECODE, PREFILL, FleetRouter, \
        ReplicaPool
    from lambdipy_tpu.runtime.faults import FaultPlan

    tmp = Path(tempfile.mkdtemp(prefix="lambdipy-disagg-rtt-"))
    bundle = _build_rtt_bundle(tmp, block=block, max_len=max_len)
    rng = np.random.default_rng(1)
    # head = the window-clamped whole-block prefix: max_len/block - 1
    # blocks, one wire chunk each (prefill_chunk == block)
    n_chunks = max_len // block - 1
    prompt_len = n_chunks * block + block // 2

    def post(base, path, payload, timeout=300):
        req = urllib.request.Request(
            f"{base}{path}", data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"}, method="POST")
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return json.loads(resp.read())

    def completion(base, row, *, max_tokens=1):
        out = post(base, "/v1/completions",
                   {"prompt": [int(t) for t in row],
                    "max_tokens": max_tokens, "temperature": 0})
        return out["choices"][0]["tokens"]

    env_extra = {"LAMBDIPY_FAULT":
                 f"prefix_walk:delay@ms={walk_ms:g},n=inf"}
    (pd, dec_url, _), (pp, pre_url, _) = (
        _spawn_replica_proc(bundle, env_extra=env_extra, tag="rtt-d"),
        _spawn_replica_proc(bundle, env_extra=env_extra, tag="rtt-p"))
    result: dict = {"mode": "disagg-rtt", "block": block,
                    "max_len": max_len, "chunks_per_ship": n_chunks,
                    "chunk_ms": chunk_ms, "walk_ms": walk_ms}
    try:
        def fresh_row():
            return [int(t) for t in rng.integers(1, 500,
                                                 size=prompt_len)]

        def run_mode(pipelined: bool) -> dict:
            pool = ReplicaPool(probe_interval=0.5, fail_threshold=2,
                               probe_timeout=10.0)
            pool.attach("dec", dec_url, role=DECODE)
            pool.attach("pre", pre_url, role=PREFILL)
            pool.probe_all()
            pool.start()
            router = FleetRouter(
                pool, affinity_on=True, block=block, max_retries=2,
                request_timeout=300, ship_window=ship_window,
                ship_pipelined=pipelined,
                faults=FaultPlan.from_spec(
                    f"kv_ship_chunk:delay@ms={chunk_ms:g},n=inf")
            ).start_background()
            base = f"http://127.0.0.1:{router.port}"
            try:
                # off-the-clock warm: compiles the walk/continuation
                # programs on both replicas so neither mode's timing
                # pays a first-use compile
                completion(base, fresh_row())
                ttfts = []
                for _ in range(requests):
                    t0 = time.monotonic()
                    completion(base, fresh_row())
                    ttfts.append(time.monotonic() - t0)
                rep = router.disagg.report()
                if rep["decode_dispatches"] < requests + 1:
                    raise AssertionError(
                        f"rtt ({'pipelined' if pipelined else 'blocking'}"
                        f"): ships did not land: {rep}")
                if pipelined and rep["ships_pipelined"] < requests:
                    raise AssertionError(
                        f"rtt: pipelined mode did not stream: {rep}")
                if rep["chunks_relayed"] < (requests + 1) * n_chunks:
                    raise AssertionError(
                        f"rtt: expected >= {(requests + 1) * n_chunks} "
                        f"relayed chunks, saw {rep['chunks_relayed']}")
                if rep["fallbacks"]:
                    raise AssertionError(
                        f"rtt: ships fell back under plain RTT: "
                        f"{rep['fallbacks']}")
                return {"ttft_median_s": round(
                            statistics.median(ttfts), 3),
                        "ttft_s": [round(t, 3) for t in ttfts],
                        "ships": rep["ships"],
                        "chunks_relayed": rep["chunks_relayed"],
                        "ship_ms_ewma": rep["ship_ms_ewma"]}
            finally:
                router.stop()
                pool.close()

        result["blocking"] = run_mode(False)
        result["pipelined"] = run_mode(True)
        ratio = (result["pipelined"]["ttft_median_s"]
                 / max(1e-9, result["blocking"]["ttft_median_s"]))
        result["ttft_ratio"] = round(ratio, 3)
        result["max_ratio"] = max_ratio
        if ratio > max_ratio:
            raise AssertionError(
                f"disagg-rtt: pipelined TTFT is {ratio:.2f}x the "
                f"blocking ship's (gate <= {max_ratio}x): {result}")

        # ---- permanent mid-stream failure: bitwise, zero errors -----
        rows = [fresh_row() for _ in range(requests)]
        refs = [completion(pre_url, row, max_tokens=4) for row in rows]
        pool = ReplicaPool(probe_interval=0.5, fail_threshold=2,
                           probe_timeout=10.0)
        pool.attach("dec", dec_url, role=DECODE)
        pool.attach("pre", pre_url, role=PREFILL)
        pool.probe_all()
        pool.start()
        router = FleetRouter(
            pool, affinity_on=True, block=block, max_retries=2,
            request_timeout=300, ship_window=ship_window,
            faults=FaultPlan.from_spec(
                "kv_ship_chunk:exception@seg=1,n=inf")
        ).start_background()
        base = f"http://127.0.0.1:{router.port}"
        try:
            errors = []
            for i, row in enumerate(rows):
                try:
                    got = completion(base, row, max_tokens=4)
                    if got != refs[i]:
                        errors.append(f"row {i}: tokens diverged")
                except Exception as e:  # noqa: BLE001
                    errors.append(f"row {i}: {type(e).__name__}: {e}")
            # dedup must not be poisoned by aborted streams: the same
            # prefix re-ships (and re-fails, and still serves) instead
            # of silently skipping
            repeat = completion(base, rows[0], max_tokens=4)
            if repeat != refs[0]:
                errors.append("repeat: tokens diverged")
            rep = router.disagg.report()
            if errors:
                raise AssertionError(
                    f"disagg-rtt failure leg: client-visible damage "
                    f"with chunks down: {errors[:3]}")
            if rep["ships"] != 0:
                raise AssertionError(
                    "disagg-rtt failure leg: a ship landed despite "
                    "the permanent chunk fault")
            if rep["fallbacks"].get("ship_chunk_fault", 0) \
                    < requests + 1:
                raise AssertionError(
                    f"disagg-rtt failure leg: expected every attempt "
                    f"(incl. the repeat) to re-ship and fault, saw "
                    f"{rep['fallbacks']}")
            if rep["ship_skips"] != 0:
                raise AssertionError(
                    "disagg-rtt failure leg: an aborted stream marked "
                    "the ship-dedup LRU")
            result["ship_chunk_failure"] = {
                "requests": len(rows) + 1, "delivered": len(rows) + 1,
                "fallbacks": rep["fallbacks"],
                "mid_stream_failures": rep["mid_stream_failures"],
                "parity": True}
        finally:
            router.stop()
            pool.close()
    finally:
        for p in (pd, pp):
            p.kill()
    result["passed"] = True
    import jax

    result["platform"] = jax.devices()[0].platform
    return result


def autoscale_record(*, block: int = 64, burst_len: int = 449,
                     walk_ms: float = 90.0, n_new: int = 8,
                     trigger_s: float = 3.5, window_s: float = 7.0,
                     burst_interval_ms: float = 600.0,
                     probe_interval_ms: float = 150.0,
                     slo_p99_ms: float = 200.0,
                     max_p99_ratio: float = 0.7,
                     dry_run_s: float = 2.5) -> dict:
    """Elastic control-plane sweep (CPU-runnable, SUBPROCESS replicas):
    an open-loop prefill-burst spike against a 2-replica MIXED fleet,
    with and without ``FleetController`` closing the loop. Three hard
    gates:

    1. RECOVERY — the controller must PROMOTE one mixed replica to the
       prefill class under the sustained queue-wait breach, and the
       autoscaled fleet's interactive queue-wait P99 (measured client-
       side from the ``queue_wait_ms`` response echo, after
       ``trigger_s``) must be <= ``max_p99_ratio`` x the static fleet's
       under the identical workload. Every delivered interactive answer
       is checked BITWISE against the direct per-replica reference, and
       the zero-loss bar holds through the live role flip: issued ==
       delivered + priced sheds, nothing silent.
    2. DETERMINISM — ``replay_decisions()`` re-runs the pure policy
       over the live snapshots with a fresh state and must reproduce
       the decision trace byte-for-byte.
    3. DRY RUN — a controller in ``dry_run`` mode over the same
       (pressured) fleet logs promote INTENTS but fires no actuator:
       zero applied actions, zero events, every role still mixed.
    """
    import tempfile
    import urllib.error
    import urllib.request
    from pathlib import Path

    import numpy as np

    from lambdipy_tpu.fleet import (MIXED, PREFILL, FleetController,
                                    FleetRouter, PolicyConfig, ReplicaPool)

    tmp = Path(tempfile.mkdtemp(prefix="lambdipy-autoscale-bench-"))
    bundle = _build_disagg_bundle(tmp, n_new=n_new, block=block,
                                  name="autoscale-bench")
    rng = np.random.default_rng(2)
    env_extra = {"LAMBDIPY_FAULT":
                 f"prefix_walk:delay@ms={walk_ms:g},n=inf"}

    def post(base, path, payload, *, headers=None, timeout=300):
        req = urllib.request.Request(
            f"{base}{path}", data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json",
                     **(headers or {})}, method="POST")
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return json.loads(resp.read())

    def completion(base, row, *, max_tokens, headers=None):
        out = post(base, "/v1/completions",
                   {"prompt": [int(t) for t in row],
                    "max_tokens": max_tokens, "temperature": 0},
                   headers=headers)
        return out["choices"][0]["tokens"], out.get("queue_wait_ms")

    def boot_pair(tag):
        out = [None, None]
        errs: list = []

        def boot(i):
            try:
                out[i] = _spawn_replica_proc(bundle, env_extra=env_extra,
                                             tag=f"{tag}{i}")
            except Exception as e:  # noqa: BLE001 — re-raised below
                errs.append(e)

        threads = [threading.Thread(target=boot, args=(i,))
                   for i in range(2)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        if errs:
            for rec in out:
                if rec is not None:
                    rec[0].kill()
            raise errs[0]
        return out

    def mk_fleet(specs):
        pool = ReplicaPool(probe_interval=0.5, fail_threshold=2,
                           probe_timeout=10.0)
        for name, url in specs:
            pool.attach(name, url, role=MIXED)
        pool.probe_all()
        pool.start()
        router = FleetRouter(pool, affinity_on=True, block=block,
                             max_retries=2, request_timeout=300)
        return router.start_background(), pool

    def bench_policy():
        # promote-only shape: util_low=0 makes demote/retire impossible
        # (no util is < 0), so the measured leg isolates ONE promote
        # instead of flapping; short sustain/cooldown fit the window
        return PolicyConfig(slo_p99_ms=slo_p99_ms,
                            slo_class="interactive", hysteresis=0.2,
                            sustain_s=0.6, lifecycle_cooldown_s=6.0,
                            knob_cooldown_s=2.0, live_floor=1,
                            min_replicas=2, max_prefill=1, util_low=0.0)

    # the interactive rows: one shared warm prefix + distinct suffixes
    # (all land on ONE affinity target — the lane the burst squeezes)
    prefix = _disagg_rows(rng, n=1, length=block)[0]
    rows = [prefix + _disagg_rows(rng, n=1, length=8)[0]
            for _ in range(32)]

    def warm_refs(urls):
        """Direct per-replica references: warms the prefix radix on
        BOTH replicas (so the role flip never strands affinity on a
        cold store) and pins the bitwise bar for every delivered
        interactive answer; also compiles the burst-shaped cold-walk
        program on both so neither measured leg pays a first-use
        compile."""
        per = []
        for url in urls:
            per.append([completion(url, row, max_tokens=n_new)[0]
                        for row in rows])
            completion(url, _disagg_rows(rng, n=1, length=burst_len)[0],
                       max_tokens=1)
        if per[0] != per[1]:
            raise AssertionError(
                "autoscale: replica pair is not bitwise identical — "
                "the parity bar below would be meaningless")
        return per[0]

    def run_leg(base, refs):
        """One open-loop window: interactive probes every
        ``probe_interval_ms`` (default lane), cold prefill bursts every
        ``burst_interval_ms`` (batch lane), all fired on timers
        regardless of completion — a closed loop would self-pace to the
        slower fleet and offer it LESS load, backwards for a recovery
        comparison. Returns (samples, accounting)."""
        lock = threading.Lock()
        samples: list = []      # (t_issued_s, queue_wait_ms)
        losses: list = []
        sheds = [0]
        issued = {"probes": 0, "bursts": 0}
        threads: list = []

        def classify(e, what):
            if isinstance(e, urllib.error.HTTPError) \
                    and e.code in (429, 503, 504) \
                    and e.headers.get("Retry-After"):
                with lock:
                    sheds[0] += 1
                return
            with lock:
                losses.append(f"{what}: {type(e).__name__}: {e}")

        def probe_once(i, t_issue):
            try:
                toks, wait = completion(base, rows[i % len(rows)],
                                        max_tokens=n_new)
                if toks != refs[i % len(rows)]:
                    with lock:
                        losses.append(f"probe {i}: tokens diverged")
                    return
                if wait is not None:
                    with lock:
                        samples.append((t_issue, float(wait)))
            except Exception as e:  # noqa: BLE001 — classified below
                classify(e, f"probe {i}")

        def burst_once(j, row):
            try:
                completion(base, row, max_tokens=1,
                           headers={"x-priority": "batch"})
            except Exception as e:  # noqa: BLE001 — classified below
                classify(e, f"burst {j}")

        # one scheduler thread owns the shared rng and both timers
        t0 = time.monotonic()
        next_probe, next_burst, i = 0.0, 0.0, 0
        while True:
            now = time.monotonic() - t0
            if now >= window_s:
                break
            if now >= next_burst:
                row = _disagg_rows(rng, n=1, length=burst_len)[0]
                th = threading.Thread(
                    target=burst_once, args=(issued["bursts"], row),
                    daemon=True)
                th.start()
                threads.append(th)
                issued["bursts"] += 1
                next_burst += burst_interval_ms / 1e3
            if now >= next_probe:
                th = threading.Thread(target=probe_once, args=(i, now),
                                      daemon=True)
                th.start()
                threads.append(th)
                i += 1
                issued["probes"] += 1
                next_probe += probe_interval_ms / 1e3
            time.sleep(0.01)
        for th in threads:  # zero-loss: every issued request completes
            th.join(timeout=120)
        if any(th.is_alive() for th in threads):
            losses.append("wedged: a request never completed")
        if losses:
            raise AssertionError(
                f"autoscale: silent losses under the spike: "
                f"{losses[:3]}")
        tail = sorted(w for ts, w in samples if ts >= trigger_s)
        if len(tail) < 8:
            raise AssertionError(
                f"autoscale: only {len(tail)} post-trigger samples — "
                f"the window measured nothing")
        p99 = tail[min(len(tail) - 1, int(0.99 * len(tail)))]
        acct = {"probes_issued": issued["probes"],
                "bursts_issued": issued["bursts"],
                "priced_sheds": sheds[0],
                "delivered": issued["probes"] + issued["bursts"]
                - sheds[0],
                "samples": len(samples), "tail_samples": len(tail),
                "p99_queue_wait_ms": round(p99, 1),
                "p50_queue_wait_ms": round(tail[len(tail) // 2], 1)}
        return p99, acct

    result: dict = {"mode": "autoscale", "block": block,
                    "burst_len": burst_len, "walk_ms": walk_ms,
                    "window_s": window_s, "trigger_s": trigger_s,
                    "slo_p99_ms": slo_p99_ms,
                    "max_p99_ratio": max_p99_ratio}

    # ---- leg 1+2: STATIC baseline, then DRY RUN on its pressure -----
    (p0, url0, _), (p1, url1, _) = boot_pair("st")
    try:
        refs = warm_refs((url0, url1))
        router, pool = mk_fleet([("st0", url0), ("st1", url1)])
        try:
            p99_static, result["static"] = run_leg(
                f"http://127.0.0.1:{router.port}", refs)
        finally:
            router.stop()
            pool.close()
        # the replicas' queue-wait reservoirs still hold the static
        # leg's breach — a dry-run controller over them must INTEND
        # the promote without touching anything
        router, pool = mk_fleet([("st0", url0), ("st1", url1)])
        ctrl = FleetController(router, config=bench_policy(),
                               interval_s=0.2, dry_run=True).start()
        try:
            time.sleep(dry_run_s)
            rep = ctrl.report()
            roles = sorted(r.role for r in pool.replicas.values())
            if rep["intents"].get("promote", 0) < 1:
                raise AssertionError(
                    f"autoscale dry-run: no promote intent logged "
                    f"under a breached fleet: {rep}")
            if rep["actions"]:
                raise AssertionError(
                    f"autoscale dry-run: an actuator fired: "
                    f"{rep['actions']}")
            if rep["events"] or roles != [MIXED, MIXED]:
                raise AssertionError(
                    f"autoscale dry-run: the fleet changed "
                    f"(events={rep['events']}, roles={roles})")
            result["dry_run"] = {"intents": rep["intents"],
                                 "ticks": rep["ticks"], "acted": False}
        finally:
            ctrl.close()
            router.stop()
            pool.close()
    finally:
        for p in (p0, p1):
            p.kill()

    # ---- leg 3: AUTOSCALED — same workload, controller live ---------
    (p0, url0, _), (p1, url1, _) = boot_pair("au")
    try:
        refs = warm_refs((url0, url1))
        router, pool = mk_fleet([("au0", url0), ("au1", url1)])
        ctrl = FleetController(router, config=bench_policy(),
                               interval_s=0.25).start()
        try:
            p99_auto, result["autoscale"] = run_leg(
                f"http://127.0.0.1:{router.port}", refs)
            rep = ctrl.report()
            roles = sorted(r.role for r in pool.replicas.values())
            if rep["actions"].get("promote", 0) < 1 \
                    or PREFILL not in roles:
                raise AssertionError(
                    f"autoscale: the controller never promoted a "
                    f"prefill replica (actions={rep['actions']}, "
                    f"roles={roles})")
            bad = [e["event"] for e in rep["events"]
                   if not e["event"].startswith("@")]
            if bad:
                raise AssertionError(
                    f"autoscale: events out of the nemesis grammar: "
                    f"{bad}")
            if not ctrl.replay_decisions():
                raise AssertionError(
                    "autoscale: the decision trace is not reproducible "
                    "from its snapshots — the policy leaked impurity")
            result["autoscale"]["controller"] = {
                "actions": rep["actions"], "intents": rep["intents"],
                "ticks": rep["ticks"], "errors": rep["errors"],
                "events": [e["event"] for e in rep["events"]],
                "replay_identical": True}
            result["autoscale"]["roles"] = roles
        finally:
            ctrl.close()
            router.stop()
            pool.close()
    finally:
        for p in (p0, p1):
            p.kill()

    ratio = p99_auto / max(1e-9, p99_static)
    result["p99_ratio"] = round(ratio, 3)
    if p99_static <= slo_p99_ms:
        raise AssertionError(
            f"autoscale: the static fleet never breached the SLO "
            f"(p99 {p99_static:.0f}ms <= {slo_p99_ms:.0f}ms) — the "
            f"spike tested nothing")
    if ratio > max_p99_ratio:
        raise AssertionError(
            f"autoscale: P99 queue-wait recovered to only "
            f"{ratio:.2f}x static (gate <= {max_p99_ratio}x): "
            f"{result}")
    result["passed"] = True
    import jax

    result["platform"] = jax.devices()[0].platform
    return result


def _build_sessions_bundle(tmp, *, n_new: int, block: int,
                           name: str = "sessions-bench"):
    """The tiny llama bundle the sessions sweep serves: continuous
    batching + prefix cache (sessions ride it), prefill_chunk pinned to
    the block width so a cold conversation walk costs one modeled
    device delay PER BLOCK (the TTFT story needs cold prefill that
    scales with history length), deterministic init params so every
    replica — and the direct reference server — is bitwise the same."""
    from lambdipy_tpu.buildengine import build_recipe
    from lambdipy_tpu.bundle import assemble_bundle
    from lambdipy_tpu.recipes.schema import load_recipe_dict

    doc = {
        "schema": 1, "name": name, "version": "0.1",
        "device": "any", "base_layer": "jax-tpu", "requires": [],
        "payload": {
            "model": "llama-tiny",
            "handler": "lambdipy_tpu.runtime.handlers:generate_handler",
            "params": "init", "dtype": "float32",
            "extra": {"max_new_tokens": str(n_new), "serve_aot": "0",
                      "warm_group_prefill": "0",
                      "prefix_cache_mb": "64",
                      "prefix_block": str(block),
                      "prefill_chunk": str(block),
                      "max_len": "512", "hidden": "64",
                      "batch_mode": "continuous",
                      "batch_max": "4", "batch_segment": "8"},
        },
    }
    result = build_recipe(load_recipe_dict(doc), tmp / "work",
                          run_smoke=False)
    bundle = tmp / "bundle"
    assemble_bundle(result, bundle, with_payload=True)
    return bundle


def _conv_prompts(seed, *, first_len, user_len, turns, vocab=500):
    """Deterministic conversation schedule: the opening prompt plus the
    per-turn user extensions (completions get appended as they arrive,
    so the full history is schedule + transcript)."""
    import numpy as np

    rng = np.random.default_rng(seed)
    first = [int(t) for t in rng.integers(1, vocab, size=first_len)]
    users = [[int(t) for t in rng.integers(1, vocab, size=user_len)]
             for _ in range(turns)]
    return first, users


def sessions_record(*, block: int = 64, first_len: int = 321,
                    user_len: int = 16, n_new: int = 24, turns: int = 3,
                    walk_ms: float = 400.0, ttft_gate: float = 0.15,
                    expiry_ttl_s: float = 2.0) -> dict:
    """Multi-turn session sweep (CPU-runnable, SUBPROCESS replicas
    behind the sticky-session router). Four claims, each a hard assert,
    run over {dense, paged} KV x {greedy, seeded-sampled} x {healthy,
    mid-conversation replica SIGKILL}:

    1. PARITY — every turn of every conversation through the fleet is
       BITWISE the direct single-server transcript, including the turns
       served right after the session's home replica is SIGKILLed
       (failover re-prefill) and after it restarts.
    2. ZERO ERRORS — no conversation turn ever surfaces a client error,
       kill and failover included.
    3. TTFT — with a healthy home, turn-2+ TTFT is <= ``ttft_gate`` x
       the cold turn-1 TTFT: the pinned, sticky-routed history skips
       the whole-history prefill (cold walk device time modeled per
       block through the deterministic ``prefix_walk`` delay site, the
       --disagg idiom — real tiny-model prefill is too cheap on CPU to
       carry a latency claim).
    4. PINS DRAIN — after every session closes (explicit DELETE fan-out
       plus one session left to LEASE EXPIRY), each live replica's
       pinned-leaf/pinned-byte accounting reads exactly zero.

    The dense fleet additionally exercises a REACHABLE-home failover
    (eject stand-in with the process alive): the session's whole-block
    KV head re-ships old home -> new home and the re-ship counter moves.

    ``first_len`` defaults to one past a block boundary so the cacheable
    turn-1 target lands block-aligned (320 = 5 x 64): warm turns whose
    growth stays inside one block then walk ZERO cold chunks, which is
    what the TTFT claim is about — the alternative alignment would
    charge every warm turn one block of walk and measure block geometry,
    not session pinning.
    """
    import tempfile
    import urllib.request
    from concurrent.futures import ThreadPoolExecutor
    from pathlib import Path

    from lambdipy_tpu.fleet import EJECTED, FleetRouter, ReplicaPool

    tmp = Path(tempfile.mkdtemp(prefix="lambdipy-sessions-bench-"))
    bundle = _build_sessions_bundle(tmp, n_new=n_new, block=block)

    def post(base, path, payload, timeout=300):
        req = urllib.request.Request(
            f"{base}{path}", data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"}, method="POST")
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return json.loads(resp.read())

    def completion(base, row, *, max_tokens, session=None, ttl=None,
                   **kw):
        body = {"prompt": [int(t) for t in row],
                "max_tokens": max_tokens,
                "temperature": kw.get("temperature", 0)}
        for k in ("seed", "top_p"):
            if k in kw:
                body[k] = kw[k]
        if session is not None:
            body["session_id"] = session
        if ttl is not None:
            body["session_ttl_s"] = ttl
        return post(base, "/v1/completions", body)["choices"][0]["tokens"]

    def metrics(base):
        with urllib.request.urlopen(f"{base}/metrics",
                                    timeout=60) as resp:
            return json.loads(resp.read())

    # the direct single-server REFERENCE (no walk delay — the delay
    # models device time, it never changes tokens): transcripts the
    # fleet must reproduce bitwise
    ref_proc, ref_url, _ = _spawn_replica_proc(bundle, tag="ref")
    ref_cache: dict = {}

    def ref_transcript(seed, *, nturns, per_turn_new, kw):
        ck = (seed, nturns, per_turn_new, tuple(sorted(kw)))
        if ck in ref_cache:
            return ref_cache[ck]
        first, users = _conv_prompts(seed, first_len=first_len,
                                     user_len=user_len, turns=nturns)
        history, out = list(first), []
        for t in range(nturns):
            toks = completion(ref_url, history,
                              max_tokens=per_turn_new, **kw)
            out.append(toks)
            history = history + toks + users[t]
        ref_cache[ck] = out
        return out

    SAMPLED = {"temperature": 0.9, "seed": 7, "top_p": 0.9}
    result: dict = {"mode": "sessions", "block": block, "n_new": n_new,
                    "turns": turns, "walk_ms": walk_ms}

    def run_fleet(label: str, paged: bool, seed_base: int) -> dict:
        env_extra = {"LAMBDIPY_FAULT":
                     f"prefix_walk:delay@ms={walk_ms:g},n=inf"}
        if paged:
            env_extra.update({"LAMBDIPY_KV_PAGED": "1",
                              "LAMBDIPY_KV_PAGES": "96"})
        procs: dict = {}
        (p0, url0, _), (p1, url1, _) = (
            _spawn_replica_proc(bundle, env_extra=env_extra,
                                tag=f"{label}0"),
            _spawn_replica_proc(bundle, env_extra=env_extra,
                                tag=f"{label}1"))
        procs["r0"] = [p0, url0]
        procs["r1"] = [p1, url1]
        pool = ReplicaPool(probe_interval=0.5, fail_threshold=1,
                           readmit_passes=2, probe_timeout=10.0)
        pool.attach("r0", url0)
        pool.attach("r1", url1)
        pool.probe_all()
        pool.start()
        router = FleetRouter(pool, affinity_on=True, block=block,
                             max_retries=2, request_timeout=300)
        router.start_background()
        base = f"http://127.0.0.1:{router.port}"
        out: dict = {}
        errors: list = []

        def turn(sid, history, per_turn_new, kw, ttl=None):
            try:
                return completion(base, history,
                                  max_tokens=per_turn_new,
                                  session=sid, ttl=ttl, **kw)
            except Exception as e:  # noqa: BLE001 — the zero-error bar
                errors.append(f"{sid}: {type(e).__name__}: {e}")
                raise

        def run_conv(sid, seed, *, nturns, per_turn_new, kw,
                     pre_turn=None):
            """Drive one conversation; returns per-turn transcripts,
            asserting bitwise parity vs the direct reference."""
            ref = ref_transcript(seed, nturns=nturns,
                                 per_turn_new=per_turn_new, kw=kw)
            first, users = _conv_prompts(seed, first_len=first_len,
                                         user_len=user_len,
                                         turns=nturns)
            history, times = list(first), []
            for t in range(nturns):
                if pre_turn is not None:
                    pre_turn(t, sid)
                t0 = time.monotonic()
                toks = turn(sid, history, per_turn_new, kw)
                times.append(time.monotonic() - t0)
                if toks != ref[t]:
                    raise AssertionError(
                        f"sessions {label}: {sid} turn {t} diverged "
                        f"from the direct transcript")
                history = history + toks + users[t]
            return times

        try:
            # off-the-clock compile warm on EACH replica directly (the
            # subprocesses do not share a compile cache): the
            # conversation shapes the TTFT gate times must hit warm
            # programs, not first-use XLA compiles
            for url in (url0, url1):
                for per_turn_new in (n_new, 1):
                    first, users = _conv_prompts(
                        900 + per_turn_new, first_len=first_len,
                        user_len=user_len, turns=2)
                    history = list(first)
                    for t in range(2):
                        toks = completion(url, history,
                                          max_tokens=per_turn_new)
                        history = history + toks + users[t]

            # -- healthy conversations, concurrent (greedy + sampled) --
            with ThreadPoolExecutor(max_workers=2) as ex:
                futs = [
                    ex.submit(run_conv, "healthy-g", seed_base + 1,
                              nturns=turns, per_turn_new=n_new, kw={}),
                    ex.submit(run_conv, "healthy-s", seed_base + 2,
                              nturns=turns, per_turn_new=n_new,
                              kw=SAMPLED),
                ]
                for f in futs:
                    f.result()
            # pins are LIVE while sessions are open — observable
            pinned_now = sum(
                metrics(rec[1])["handler"]["prefix_cache"]
                ["pinned_leaves"] for rec in procs.values())
            if pinned_now <= 0:
                raise AssertionError(
                    f"sessions {label}: no pinned leaves while two "
                    f"conversations are open — pins are not engaging")
            out["healthy"] = {"conversations": 2, "turns": turns,
                              "pinned_leaves_live": pinned_now}

            # -- TTFT: cold turn 1 vs sticky pinned turns 2+ -----------
            times = run_conv("ttft", seed_base + 3, nturns=turns,
                             per_turn_new=1, kw={})
            t_cold, t_warm = times[0], min(times[1:])
            out["ttft"] = {"cold_s": round(t_cold, 3),
                           "warm_s": round(t_warm, 3),
                           "ratio": round(t_warm / t_cold, 4),
                           "gate": ttft_gate}
            if t_warm > ttft_gate * t_cold:
                raise AssertionError(
                    f"sessions {label}: turn-2+ TTFT {t_warm:.3f}s is "
                    f"{t_warm / t_cold:.2f}x cold {t_cold:.3f}s "
                    f"(gate {ttft_gate}x) — the pinned sticky path is "
                    f"not skipping the history prefill")

            # -- mid-conversation SIGKILL of the session's home --------
            kill_turns = turns + (1 if not paged else 0)
            refs = {
                "kill-g": (seed_base + 4, n_new, {}),
                "kill-s": (seed_base + 5, n_new, SAMPLED),
            }
            convs = {}
            for sid, (seed, ptn, kw) in refs.items():
                first, users = _conv_prompts(seed, first_len=first_len,
                                             user_len=user_len,
                                             turns=kill_turns)
                convs[sid] = {
                    "history": list(first), "users": users, "kw": kw,
                    "ref": ref_transcript(seed, nturns=kill_turns,
                                          per_turn_new=ptn, kw=kw)}

            def kill_step(sid, t):
                c = convs[sid]
                toks = turn(sid, c["history"], n_new, c["kw"])
                if toks != c["ref"][t]:
                    raise AssertionError(
                        f"sessions {label}: {sid} turn {t} diverged "
                        f"(kill case)")
                c["history"] = c["history"] + toks + c["users"][t]

            for sid in convs:
                kill_step(sid, 0)
            home = router._session_map["kill-g"]["home"]
            survivor = "r1" if home == "r0" else "r0"
            failovers_before = router.sessions.report()["failovers"]
            procs[home][0].kill()
            deadline = time.monotonic() + 30
            while pool.replicas[home].state != EJECTED:
                if time.monotonic() > deadline:
                    raise AssertionError(
                        f"sessions {label}: {home} not ejected after "
                        f"SIGKILL")
                time.sleep(0.1)
            # the surviving turns: zero errors, bitwise parity — the
            # failover's local re-prefill IS the recovery path. Both
            # conversations advance concurrently, turn-aligned (a
            # conversation's own turns are inherently sequential).
            for t in range(1, turns):
                with ThreadPoolExecutor(max_workers=2) as ex:
                    list(ex.map(lambda sid, tt=t: kill_step(sid, tt),
                                convs))
            srep = router.sessions.report()
            if srep["failovers"] <= failovers_before:
                raise AssertionError(
                    f"sessions {label}: SIGKILL never triggered a "
                    f"session failover: {srep}")
            if srep["reship_fallbacks"].get("old_home_unreachable",
                                            0) < 1:
                raise AssertionError(
                    f"sessions {label}: dead-home failover was not "
                    f"counted as old_home_unreachable: {srep}")
            out["kill"] = {
                "killed": home, "survivor": survivor,
                "failovers": srep["failovers"] - failovers_before,
                "reship_fallbacks": dict(srep["reship_fallbacks"]),
            }

            if not paged:
                # restart the killed replica at its OLD URL: the pool
                # readmits it and the conversation keeps serving
                port = int(procs[home][1].rsplit(":", 1)[1])
                proc, url, _ = _spawn_replica_proc(
                    bundle, env_extra=env_extra, tag=f"{label}-re",
                    port=port)
                procs[home][0] = proc
                deadline = time.monotonic() + 120
                while not pool.replicas[home].routable:
                    if time.monotonic() > deadline:
                        raise AssertionError(
                            f"sessions {label}: {home} never readmitted "
                            f"after restart")
                    time.sleep(0.2)
                kill_step("kill-g", turns)  # one post-restart turn
                out["kill"]["restarted"] = True

                # -- reachable-home failover: the KV RE-SHIP leg -------
                run_conv("reship", seed_base + 6, nturns=1,
                         per_turn_new=n_new, kw={})
                rhome = router._session_map["reship"]["home"]
                reships_before = router.sessions.report()["reships"]
                pool.replicas[rhome].state = EJECTED  # drain stand-in
                first, users = _conv_prompts(seed_base + 6,
                                             first_len=first_len,
                                             user_len=user_len,
                                             turns=2)
                ref2 = ref_transcript(seed_base + 6, nturns=2,
                                      per_turn_new=n_new, kw={})
                history = list(first) + ref2[0] + users[0]
                toks = turn("reship", history, n_new, {})
                if toks != ref2[1]:
                    raise AssertionError(
                        f"sessions {label}: re-ship turn diverged")
                srep = router.sessions.report()
                if srep["reships"] <= reships_before:
                    raise AssertionError(
                        f"sessions {label}: reachable-home failover "
                        f"did not re-ship the session KV: {srep}")
                out["reship"] = {"from": rhome,
                                 "reships": srep["reships"]}

            # -- pins drain to zero: DELETE fan-out + lease expiry -----
            exp_sid = "expiry"
            run_conv(exp_sid, seed_base + 7, nturns=1, per_turn_new=1,
                     kw={})
            # tighten the lease AFTER the turn: renew with a short ttl
            hist_first, _ = _conv_prompts(seed_base + 7,
                                          first_len=first_len,
                                          user_len=user_len, turns=1)
            turn(exp_sid, hist_first, 1, {}, ttl=expiry_ttl_s)
            for sid in ("healthy-g", "healthy-s", "ttft", "kill-g",
                        "kill-s", "reship"):
                req = urllib.request.Request(
                    f"{base}/v1/sessions/{sid}", method="DELETE")
                try:
                    urllib.request.urlopen(req, timeout=30).read()
                except Exception:  # noqa: BLE001 — missing sessions ok
                    pass
            time.sleep(expiry_ttl_s + 0.5)  # the expiry session lapses
            pins = {}
            for name, rec in procs.items():
                if pool.replicas[name].state == EJECTED:
                    continue  # died with its pins; nothing to drain
                pc = metrics(rec[1])["handler"]["prefix_cache"]
                pins[name] = {"pinned_leaves": pc["pinned_leaves"],
                              "pinned_bytes": pc["pinned_bytes"],
                              "sessions_active": pc["sessions_active"],
                              "pin_expiries": pc["pin_expiries"]}
                if pc["pinned_leaves"] != 0 or pc["pinned_bytes"] != 0 \
                        or pc["sessions_active"] != 0:
                    raise AssertionError(
                        f"sessions {label}: pins did not return to "
                        f"zero on {name}: {pc}")
            if sum(p["pin_expiries"] for p in pins.values()) < 1:
                raise AssertionError(
                    f"sessions {label}: the lease-expiry session never "
                    f"lapsed: {pins}")
            out["pins_zero"] = pins
            if errors:
                raise AssertionError(
                    f"sessions {label}: client-visible errors: "
                    f"{errors[:3]}")
            out["client_errors"] = 0
            return out
        finally:
            router.stop()
            pool.close()
            for rec in procs.values():
                rec[0].kill()

    try:
        result["dense"] = run_fleet("dense", paged=False, seed_base=100)
        result["paged"] = run_fleet("paged", paged=True, seed_base=200)
    finally:
        ref_proc.kill()
    result["passed"] = True
    import jax

    result["platform"] = jax.devices()[0].platform
    return result


def fleet_record(*, replicas: int = 2, requests_per_group: int = 6,
                 groups: int = 2, prefix_len: int = 64, suffix_len: int = 8,
                 n_new: int = 8, block: int = 16) -> dict:
    """Fleet serving sweep (CPU-runnable): ``replicas`` in-process bundle
    servers behind the prefix-affinity router vs ONE replica hit
    directly, on a shared-prefix workload (``groups`` distinct shared
    prefixes via the --shared-prefix generator). Asserts BITWISE output
    parity between the router-fronted and direct responses (greedy, so
    platform-free), and reports throughput for both plus the router's
    affinity hit rate and the fleet-aggregate prefix-cache hit rate —
    the claim being measured is that affinity routing keeps the radix
    cache concentrated instead of diluted 1/N."""
    import tempfile
    import urllib.request
    from concurrent.futures import ThreadPoolExecutor
    from pathlib import Path

    import numpy as np

    import jax

    from lambdipy_tpu.fleet import FleetRouter, ReplicaPool
    from lambdipy_tpu.runtime.server import BundleServer

    tmp = Path(tempfile.mkdtemp(prefix="lambdipy-fleet-bench-"))
    bundle = _build_fleet_bundle(tmp, n_new=n_new, block=block)

    rng = np.random.default_rng(0)
    rows = [row for _ in range(groups)
            for row in _shared_prefix_rows(rng,
                                           n_requests=requests_per_group,
                                           prefix_len=prefix_len,
                                           suffix_len=suffix_len,
                                           vocab=512)]

    def post(url: str, payload: dict) -> dict:
        req = urllib.request.Request(
            url, data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"}, method="POST")
        with urllib.request.urlopen(req, timeout=600) as resp:
            return json.loads(resp.read())

    def completion(base: str, row: list) -> list:
        out = post(f"{base}/v1/completions",
                   {"prompt": row, "max_tokens": n_new, "temperature": 0})
        return out["choices"][0]["tokens"]

    # -- direct: one replica, no router --------------------------------------
    direct = BundleServer(bundle, warmup=False).start_background()
    base = f"http://127.0.0.1:{direct.port}"
    completion(base, rows[0])  # compile warm, off the clock
    t0 = time.monotonic()
    direct_out = [completion(base, row) for row in rows]
    direct_s = time.monotonic() - t0
    direct.stop()

    # -- fleet: N replicas behind the affinity router ------------------------
    servers = [BundleServer(bundle, warmup=False).start_background()
               for _ in range(replicas)]
    pool = ReplicaPool(probe_interval=0.5)
    for i, s in enumerate(servers):
        pool.attach(f"r{i}", f"http://127.0.0.1:{s.port}")
    pool.probe_all()
    pool.start()
    router = FleetRouter(pool, affinity_on=True,
                         block=block).start_background()
    rbase = f"http://127.0.0.1:{router.port}"
    try:
        completion(rbase, rows[0])  # compile warm on the affinity target
        t0 = time.monotonic()
        with ThreadPoolExecutor(max_workers=4) as ex:
            fleet_out = list(ex.map(lambda row: completion(rbase, row),
                                    rows))
        fleet_s = time.monotonic() - t0
        if any(a != b for a, b in zip(direct_out, fleet_out)):
            raise AssertionError(
                "fleet parity broke: router-fronted tokens != direct "
                "single-replica tokens")
        with urllib.request.urlopen(f"{rbase}/metrics", timeout=30) as resp:
            metrics = json.loads(resp.read())
    finally:
        router.stop()
        pool.close()
        for s in servers:
            s.stop()
    total_new = len(rows) * n_new
    return {
        "mode": "fleet",
        "platform": jax.devices()[0].platform,
        "replicas": replicas,
        "n_requests": len(rows),
        "groups": groups,
        "prefix_len": prefix_len,
        "suffix_len": suffix_len,
        "block": block,
        "parity": True,
        "direct_tok_s": round(total_new / direct_s, 1),
        "fleet_tok_s": round(total_new / fleet_s, 1),
        "affinity_hit_rate":
            metrics["router"]["affinity"]["hit_rate"],
        "fleet_prefix_cache": metrics["fleet"]["prefix_cache"],
        "routed": {name: rep["routed"]
                   for name, rep in metrics["pool"].items()},
    }


def decode_window_record(*, lens=(16, 48, 200), cache_len: int = 256,
                         n_new: int = 24, segment: int = 8,
                         extra: dict | None = None) -> dict:
    """Decode-window sweep: rows of different prompt lengths decode to
    ``n_new`` tokens through (a) the solo full-window dense path and
    (b) the continuous engine's length-aware window-bucketed segments,
    asserting TOKEN PARITY per length and that the measured KV-read
    ``savings_ratio`` (window bytes / full-window bytes, from
    ``DecodeWindowStats``) scales with the row's actual context —
    strictly below 1 for short rows and monotone in prompt length. The
    roofline model's analytic per-step byte counts ride along. CPU-
    runnable at tiny dims: the parity + scaling claims are platform-free
    (the engine's XLA window bucketing is what the sweep measures; the
    TPU blocked kernel's numbers come from scripts/bench_kernels.py)."""
    import numpy as np

    import jax

    from lambdipy_tpu.models import registry
    from lambdipy_tpu.runtime.continuous import ContinuousBatcher
    from lambdipy_tpu.utils import roofline

    dims = {"vocab_size": 2048, "hidden": 128, "layers": 2, "heads": 4,
            "kv_heads": 2, "mlp": 256, "max_len": cache_len}
    dims.update(extra or {})
    adapter = registry.get("llama3-8b").build(dtype="float32", extra=dims)
    cfg = adapter.config
    params = jax.device_put(adapter.init_params(seed=0))
    server = adapter.make_server(params)

    rng = np.random.default_rng(0)
    rows_rec = []
    ratios = []
    # the monotonicity assertion below compares ratios in prompt-length
    # order — sort so an unsorted --lens can't masquerade as a regression
    lens = sorted(lens)
    for L in lens:
        if L + n_new > cache_len:
            raise ValueError(f"len {L} + n_new {n_new} exceeds cache_len")
        row = rng.integers(1, cfg.vocab_size, L).tolist()
        solo = server.generate(row, max_new_tokens=n_new)
        # fresh engine per length: its decode-window counters are then
        # exactly this row's segments
        engine = ContinuousBatcher(server, slots=2, segment=segment,
                                   cache_len=cache_len)
        t0 = time.monotonic()
        out = engine.generate(row, max_new_tokens=n_new)
        wall_ms = (time.monotonic() - t0) * 1e3
        if not np.array_equal(solo, out):
            raise AssertionError(
                f"decode-window parity broke at prompt len {L}: windowed "
                "engine tokens != dense solo tokens")
        win = engine.stats()["decode_window"]
        # analytic bytes at the mean decode position, full window vs the
        # sweep's mean dispatched window
        mean_pos = L + n_new // 2
        full_cost = roofline.llama_decode_step_cost(
            cfg, batch=1, cache_len=cache_len)
        mean_window = (win["window_tokens"] / max(1, n_new))
        win_cost = roofline.llama_decode_window_cost(
            cfg, batch=1, window_len=int(mean_window), active_len=mean_pos)
        rows_rec.append({
            "prompt_len": L,
            "savings_ratio": win["savings_ratio"],
            "attended_ratio": win["attended_ratio"],
            "buckets": win["buckets"],
            "wall_ms": round(wall_ms, 1),
            "kv_bytes_step_full": full_cost.hbm_bytes
            - roofline.llama_weight_bytes(cfg),
            "kv_bytes_step_windowed": win_cost.hbm_bytes
            - roofline.llama_weight_bytes(cfg),
        })
        ratios.append(win["savings_ratio"])
    # the load-bearing claims: short rows SAVE (ratio < 1) and savings
    # shrink monotonically as the active context approaches the window
    if not ratios[0] < 1.0:
        raise AssertionError(
            f"shortest row saved nothing: savings_ratio={ratios[0]}")
    if any(a > b for a, b in zip(ratios, ratios[1:])):
        raise AssertionError(
            f"savings_ratio not monotone in prompt length: {ratios}")
    return {
        "mode": "decode_window",
        "platform": jax.devices()[0].platform,
        "cache_len": cache_len,
        "n_new": n_new,
        "segment": segment,
        "parity": True,
        "rows": rows_rec,
    }


def long_context_record(*, multipliers=(8, 16, 32), cache_len: int = 128,
                        block: int = 16, n_new: int = 32,
                        segment: int = 8, stall_frac_gate: float = 0.10,
                        toks_smooth_gate: float = 4.0,
                        ttft_slack: float = 3.0, timing_reps: int = 3,
                        extra: dict | None = None) -> dict:
    """Long-context capacity sweep (CPU-runnable): one FIXED page
    budget — a single compiled window plus two slack pages — serves
    logical contexts at ``multipliers`` x the compiled window through
    the sliding-window runner with host offload, and the gate holds the
    tier to the serve-path bar:

    1. NO SHEDS — every context up to the largest multiplier completes
       inside the fixed arena; the pool's ``sheds`` counter stays zero
       (capacity comes from the host tier, not from refusing work).
    2. PARITY — a context that fits the compiled window decodes BITWISE
       the dense solo server (base=0 collapses the windowed programs
       onto the plain paged twin), and the longest sweep point repeats
       deterministically.
    3. SMOOTH DEGRADATION — decode tok/s at each multiplier stays
       within ``toks_smooth_gate`` x of the previous point (no cliff as
       the offloaded fraction grows), and TTFT grows no worse than
       ``ttft_slack`` x proportionally to context (prefill is O(ctx);
       a superlinear blowup means the slide or spill path regressed).
    4. BOUNDED STALLS — with ``resident_cap`` forcing real churn, the
       decode-cursor prefetch keeps the re-online stall fraction
       (``stall_s`` / decode wall) <= ``stall_frac_gate`` and the leaf
       template is encoded exactly ONCE for the whole sweep.
    """
    import numpy as np

    import jax

    from lambdipy_tpu.models import registry
    from lambdipy_tpu.models.llama import init_page_arena, page_kv_bytes
    from lambdipy_tpu.runtime.longctx import LongContextRunner
    from lambdipy_tpu.runtime.pagepool import PagePool, page_width

    dims = {"vocab_size": 2048, "hidden": 128, "layers": 2, "heads": 4,
            "kv_heads": 2, "mlp": 256, "max_len": cache_len}
    dims.update(extra or {})
    adapter = registry.get("llama3-8b").build(dtype="float32", extra=dims)
    cfg = adapter.config
    params = jax.device_put(adapter.init_params(seed=0))
    server = adapter.make_server(params)

    multipliers = sorted(int(m) for m in multipliers)
    page = page_width(cfg.max_len, block)
    # the FIXED budget: one compiled window of pages + 2 slack (NULL
    # page rides extra) — the 32x context must fit in exactly this
    n_pages = cfg.max_len // page + 1 + 2
    pool = PagePool(n_pages=n_pages, page=page,
                    page_bytes=page_kv_bytes(cfg, page),
                    make_arena=lambda: init_page_arena(cfg, n_pages,
                                                       page))
    runner = LongContextRunner(
        server, pool, segment=segment,
        max_logical_ctx=(multipliers[-1] + 1) * cfg.max_len)
    # churn: cap residency below the view so the slide really spills
    # and the prefetch path carries the sweep
    churn = LongContextRunner(
        server, pool, segment=segment,
        max_logical_ctx=(multipliers[-1] + 1) * cfg.max_len,
        resident_cap=runner.n_view - 1)

    rng = np.random.default_rng(0)

    # parity leg: a within-window row through the runner is bitwise the
    # dense solo path
    short = rng.integers(1, cfg.vocab_size, cfg.max_len // 2).tolist()
    if not np.array_equal(runner.generate(short, max_new_tokens=n_new),
                          server.generate(short, max_new_tokens=n_new)):
        raise AssertionError(
            "long-context parity broke: within-window runner tokens != "
            "dense solo tokens")

    rows_rec, ttfts, toks = [], [], []
    for mult in multipliers:
        row = rng.integers(1, cfg.vocab_size,
                           mult * cfg.max_len).tolist()
        # warm pass first: the slide/offload programs compile on their
        # first use at each shape and would otherwise be billed to TTFT
        churn.generate(row, max_new_tokens=1)
        # decode_s is the DIFFERENCE of two close wall clocks (the
        # prefill dominates both calls), so one noisy sample on a
        # loaded 1-core box can land at ~0 or 3x true — median the
        # per-rep pairs instead of trusting a single subtraction
        ttft_samples, decode_samples = [], []
        for _ in range(max(1, timing_reps)):
            t0 = time.monotonic()
            churn.generate(row, max_new_tokens=1)
            t1 = time.monotonic()
            out = churn.generate(row, max_new_tokens=n_new)
            t2 = time.monotonic()
            ttft_samples.append(t1 - t0)
            decode_samples.append((t2 - t1) - (t1 - t0))
        ttft = sorted(ttft_samples)[len(ttft_samples) // 2]
        decode_s = max(
            1e-6, sorted(decode_samples)[len(decode_samples) // 2])
        tok_s = n_new / decode_s
        if mult == multipliers[-1]:
            out2 = churn.generate(row, max_new_tokens=n_new)
            if not np.array_equal(out, out2):
                raise AssertionError(
                    f"{mult}x context not deterministic across runs")
        rows_rec.append({"multiplier": mult,
                         "logical_ctx": mult * cfg.max_len,
                         "ttft_s": round(ttft, 4),
                         "tok_s": round(tok_s, 2)})
        ttfts.append(ttft)
        toks.append(tok_s)

    pstats = pool.stats()
    if pstats["sheds"] != 0:
        raise AssertionError(
            f"long-context sweep shed work: sheds={pstats['sheds']} — "
            "the fixed budget must serve every context via offload")
    if pool.free_count() != pool.capacity_pages:
        raise AssertionError("page leak across the sweep")
    for (a, b), (ma, mb) in zip(zip(toks, toks[1:]),
                                zip(multipliers, multipliers[1:])):
        if b < a / toks_smooth_gate:
            raise AssertionError(
                f"tok/s cliff {ma}x->{mb}x: {a:.1f} -> {b:.1f} "
                f"(gate {toks_smooth_gate}x)")
        if ttfts[multipliers.index(mb)] > (
                ttfts[multipliers.index(ma)] * (mb / ma) * ttft_slack):
            raise AssertionError(
                f"TTFT superlinear {ma}x->{mb}x: "
                f"{ttfts[multipliers.index(ma)]:.3f}s -> "
                f"{ttfts[multipliers.index(mb)]:.3f}s")
    rep = churn.report()
    decode_wall = sum(n_new / t for t in toks)
    stall_frac = rep["stall_s"] / max(decode_wall, 1e-9)
    if stall_frac > stall_frac_gate:
        raise AssertionError(
            f"re-online stall fraction {stall_frac:.3f} exceeds gate "
            f"{stall_frac_gate} (stall_s={rep['stall_s']})")
    if rep["template_encodes"] != 1:
        raise AssertionError(
            f"hot loop re-encoded the leaf template: "
            f"template_encodes={rep['template_encodes']}")
    if rep["spill_pages"] <= 0:
        raise AssertionError("sweep never offloaded a page — the churn "
                             "leg is not exercising the host tier")
    return {
        "mode": "long_context",
        "platform": jax.devices()[0].platform,
        "compiled_window": cfg.max_len,
        "page_budget": n_pages,
        "n_new": n_new,
        "segment": segment,
        "parity": True,
        "sheds": pstats["sheds"],
        "stall_fraction": round(stall_frac, 4),
        "prefetch_hit_rate": rep["prefetch_hit_rate"],
        "spill_pages": rep["spill_pages"],
        "reonline_pages": rep["reonline_pages"],
        "template_encodes": rep["template_encodes"],
        "rows": rows_rec,
    }


def pipeline_record(*, depths=(1, 2), rtts_ms=(0.0, 20.0, 66.0),
                    n_requests: int = 2, prompt_len: int = 12,
                    n_new: int = 64, segment: int = 16, slots: int = 4,
                    reps: int = 2, extra: dict | None = None) -> dict:
    """Pipelined-engine sweep (CPU-runnable): the same concurrent
    workload decodes through the continuous engine at each
    ``pipeline_depth``, with a SYNTHETIC per-fetch RTT injected into the
    collector to model the remote-tunnel transport (the ~66 ms per
    ``device_get`` the engine comment records; the sleep starts after
    device compute completes and stalls only that fetch, exactly like a
    tunnel RTT). Asserts BITWISE token parity across depths (and vs the
    solo server), and that depth 2 beats depth 1 on tok/s at every
    synthetic RTT >= 20 ms — the pipelining claim: with >= 2 segments in
    flight, device compute hides under the fetch + host-bookkeeping
    window that a depth-1 loop serializes. Reports per-depth tok/s,
    overlap ratio and the ``batching.pipeline`` counters."""
    from concurrent.futures import ThreadPoolExecutor

    import numpy as np

    import jax

    from lambdipy_tpu.models import registry
    from lambdipy_tpu.runtime.continuous import ContinuousBatcher

    dims = {"vocab_size": 2048, "hidden": 128, "layers": 2, "heads": 4,
            "kv_heads": 2, "mlp": 256,
            "max_len": max(256, 2 * (prompt_len + n_new))}
    dims.update(extra or {})
    adapter = registry.get("llama3-8b").build(dtype="float32", extra=dims)
    params = jax.device_put(adapter.init_params(seed=0))
    server = adapter.make_server(params)

    rng = np.random.default_rng(0)
    rows = [rng.integers(1, adapter.config.vocab_size, prompt_len).tolist()
            for _ in range(n_requests)]
    solo = [server.generate(r, max_new_tokens=n_new) for r in rows]

    def run_engine(depth: int, rtt: float):
        engine = ContinuousBatcher(server, slots=slots, segment=segment,
                                   pipeline_depth=depth,
                                   synthetic_fetch_rtt_ms=rtt)
        t0 = time.monotonic()
        with ThreadPoolExecutor(max_workers=n_requests) as ex:
            outs = list(ex.map(
                lambda row: engine.generate(row, max_new_tokens=n_new),
                rows))
        wall = time.monotonic() - t0
        # generate() returns when the collector marks the last row done,
        # but the engine thread may still be draining up to depth-1
        # garbage segments (each paying the synthetic RTT) before it
        # closes the episode — wait for idle so the reported pipeline
        # counters are complete, while tok/s stays the client-visible
        # wall measured above
        with engine._lock:
            while engine._engine_running:
                engine._lock.wait(0.05)
        return outs, wall, engine.stats()

    # warm off the clock: compile the group prefill, pack, and every
    # window-bucket segment variant this workload dispatches (the
    # position sequence is identical across the timed runs)
    for depth in sorted(set(depths)):
        run_engine(depth, 0.0)

    total_new = n_requests * n_new
    rows_rec = []
    for rtt in sorted(rtts_ms):
        per = {}
        for depth in sorted(set(depths)):
            best = None
            for _ in range(max(1, reps)):
                outs, wall, stats = run_engine(depth, rtt)
                for i, out in enumerate(outs):
                    if not np.array_equal(out, solo[i]):
                        raise AssertionError(
                            f"pipeline parity broke: depth={depth} "
                            f"rtt={rtt}ms request {i} tokens != solo")
                if best is None or wall < best[0]:
                    best = (wall, stats)
            wall, stats = best
            pipe = stats["pipeline"]
            per[depth] = {
                "tok_s": round(total_new / wall, 1),
                "wall_ms": round(wall * 1e3, 1),
                "overlap_ratio": pipe["overlap_ratio"],
                "in_flight": pipe["in_flight"],
                "wasted_overdecode_tokens":
                    pipe["wasted_overdecode_tokens"],
                "drains": pipe["drains"],
            }
        rec = {"rtt_ms": rtt,
               "depths": {str(d): v for d, v in per.items()}}
        if 1 in per and 2 in per:
            rec["speedup_d2_vs_d1"] = round(
                per[2]["tok_s"] / per[1]["tok_s"], 3)
            if rtt >= 20.0 and per[2]["tok_s"] <= per[1]["tok_s"]:
                # the load-bearing claim: with a nonzero fetch RTT the
                # double-buffered loop must beat the synchronous one
                raise AssertionError(
                    f"pipeline depth 2 regressed below depth 1 at "
                    f"synthetic RTT {rtt}ms: {per[2]['tok_s']} <= "
                    f"{per[1]['tok_s']} tok/s")
        rows_rec.append(rec)
    return {
        "mode": "pipeline",
        "platform": jax.devices()[0].platform,
        "n_requests": n_requests,
        "prompt_len": prompt_len,
        "n_new": n_new,
        "segment": segment,
        "slots": slots,
        "parity": True,
        "rows": rows_rec,
    }


def paged_record(*, n_requests: int = 4, prefix_len: int = 512,
                 suffix_len: int = 8, n_new: int = 16, segment: int = 8,
                 slots: int = 4, block: int = 64,
                 depths=(1, 2), extra: dict | None = None) -> dict:
    """Paged-KV sweep (CPU-runnable): the vLLM-style page-arena engine
    (runtime/pagepool.py) against the dense window-per-slot engine on
    the same model, asserting the three claims the refactor makes:

    1. BITWISE PARITY — greedy + seeded-sampled, cold rows and
       prefix-cache hits, streamed and non-streamed, under concurrent
       engine traffic, at pipeline depths 1 and 2: paged tokens equal
       the solo server's (and therefore the dense engine's) exactly.
    2. ZERO-COPY HITS — on a repeated ``prefix_len``-token prefix the
       paged store's ``assembly_bytes_peak`` stays 0 while the dense
       store (prefix entries rotating through a size-1 server LRU, the
       multi-tenant steady state) re-assembles a full-window cache per
       alternating hit; shared-page refcounts > 1 are observed on the
       live pool while hit rows decode.
    3. TOKEN-BOUNDED CAPACITY — under the SAME HBM budget the dense
       engine allocates (slots x window), a mixed-length workload
       admits strictly more concurrent rows through page accounting
       than through window accounting, margin printed.
    """
    from concurrent.futures import ThreadPoolExecutor

    import numpy as np

    import jax

    from lambdipy_tpu.models import registry
    from lambdipy_tpu.models.llama import init_page_arena, page_kv_bytes
    from lambdipy_tpu.runtime.continuous import ContinuousBatcher
    from lambdipy_tpu.runtime.pagepool import (PagePool, PagesExhausted,
                                               page_width)
    from lambdipy_tpu.runtime.prefixstore import PrefixStore

    dims = {"vocab_size": 2048, "hidden": 128, "layers": 2, "heads": 4,
            "kv_heads": 2, "mlp": 256,
            "max_len": max(1024, 2 * (prefix_len + suffix_len + n_new))}
    dims.update(extra or {})
    adapter = registry.get("llama3-8b").build(dtype="float32", extra=dims)
    cfg = adapter.config
    params = jax.device_put(adapter.init_params(seed=0))
    # prefix_cache_max=1 models the multi-tenant steady state: dense
    # assembled entries rotate out of the server LRU, so every
    # alternating hit pays a fresh concat_cache_blocks assembly — the
    # copy the paged path deletes
    server = adapter.make_server(params, prefix_cache_max=1)

    rng = np.random.default_rng(0)
    rows_a = _shared_prefix_rows(rng, n_requests=n_requests,
                                 prefix_len=prefix_len,
                                 suffix_len=suffix_len,
                                 vocab=cfg.vocab_size)
    rows_b = _shared_prefix_rows(rng, n_requests=n_requests,
                                 prefix_len=prefix_len,
                                 suffix_len=suffix_len,
                                 vocab=cfg.vocab_size)
    cold = [rng.integers(1, cfg.vocab_size, 12).tolist()
            for _ in range(n_requests)]
    sample_kw = dict(temperature=0.8, top_k=32, seed=11)

    # solo references (unrouted full prompts) — the bitwise oracle
    refs = {}
    for i, r in enumerate(rows_a + rows_b + cold):
        refs[tuple(r)] = server.generate(r, max_new_tokens=n_new)
    refs_s = {tuple(r): server.generate(r, max_new_tokens=n_new,
                                        **sample_kw)
              for r in (rows_a[:2] + cold[:2])}

    window_pages_budget = None
    page = page_width(cfg.max_len, block)

    def mk_paged(depth: int):
        n_pages = slots * (cfg.max_len // page) + 1
        pool = PagePool(n_pages=n_pages, page=page,
                        page_bytes=page_kv_bytes(cfg, page),
                        make_arena=lambda n=n_pages: init_page_arena(
                            cfg, n, page))
        eng = ContinuousBatcher(server, slots=slots, segment=segment,
                                pipeline_depth=depth, page_pool=pool)
        store = PrefixStore(server, block=block, budget_mb=64, pool=pool)
        eng.prefix_pages_fn = store.acquire_pages
        return eng, store, pool

    def routed(eng, store, row, sampled=False, stream=False):
        m = store.route(row)
        kw = dict(sample_kw) if sampled else {}
        pfx = np.asarray(row[:m], np.int32) if m > 0 else None
        suf = np.asarray(row[m:], np.int32) if m > 0 else row
        if stream:
            return np.concatenate(
                list(eng.generate_stream(suf, max_new_tokens=n_new,
                                         prefix=pfx, **kw)), axis=1)
        return eng.generate(suf, max_new_tokens=n_new, prefix=pfx, **kw)

    parity_checked = 0
    max_ref_seen = 1
    per_depth = {}
    for depth in sorted(set(depths)):
        eng, store, pool = mk_paged(depth)
        # cold rows (group-prefill path) + first tenant's cold walk
        for r in cold:
            out = eng.generate(r, max_new_tokens=n_new)
            assert np.array_equal(out, refs[tuple(r)]), \
                f"paged cold parity broke at depth {depth}"
            parity_checked += 1
        first = routed(eng, store, rows_a[0])
        assert np.array_equal(first, refs[tuple(rows_a[0])])
        parity_checked += 1
        # concurrent prefix hits + cold traffic, polled for live sharing
        done = []

        def burst():
            with ThreadPoolExecutor(max_workers=2 * n_requests) as ex:
                futs = [ex.submit(routed, eng, store, r)
                        for r in rows_a[1:]]
                futs += [ex.submit(eng.generate, c, max_new_tokens=n_new)
                         for c in cold]
                for f in futs:
                    done.append(f.result())

        import threading

        t = threading.Thread(target=burst)
        t.start()
        while t.is_alive():
            max_ref_seen = max(max_ref_seen,
                               pool.stats()["max_refcount"])
            time.sleep(0.001)
        t.join()
        for out, r in zip(done, rows_a[1:] + cold):
            assert np.array_equal(out, refs[tuple(r)]), \
                f"paged concurrent parity broke at depth {depth}"
            parity_checked += 1
        # seeded-sampled (prefix hit + cold) and streamed hit
        for r in rows_a[:2]:
            out = routed(eng, store, r, sampled=True)
            assert np.array_equal(out, refs_s[tuple(r)]), \
                f"paged sampled parity broke at depth {depth}"
            parity_checked += 1
        for r in cold[:2]:
            out = eng.generate(r, max_new_tokens=n_new, **sample_kw)
            assert np.array_equal(out, refs_s[tuple(r)]), \
                f"paged sampled cold parity broke at depth {depth}"
            parity_checked += 1
        streamed = routed(eng, store, rows_a[1], stream=True)
        assert np.array_equal(streamed[:, :n_new],
                              refs[tuple(rows_a[1])]), \
            f"paged streamed parity broke at depth {depth}"
        parity_checked += 1
        # second tenant alternates in, then tenant A hits again —
        # the rotation that forces the DENSE path to re-assemble
        for r in rows_b[:2] + rows_a[:2]:
            out = routed(eng, store, r)
            assert np.array_equal(out, refs[tuple(r)])
            parity_checked += 1
        with eng._lock:
            while eng._engine_running:
                eng._lock.wait(0.05)
        pool.check_invariants()
        st, ps = store.stats(), pool.stats()
        assert st["assembly_bytes_peak"] == 0, \
            f"paged path assembled: {st}"
        per_depth[depth] = {
            "prefix_hits": st["hits"],
            "assembly_bytes_peak": st["assembly_bytes_peak"],
            "pool_shares": ps["shares"],
            "pool_sheds": ps["sheds"],
        }
        window_pages_budget = pool.window_pages

    # the DENSE comparison point: same alternating-tenant hit pattern
    dense_store = PrefixStore(server, block=block, budget_mb=64)
    dense_eng = ContinuousBatcher(server, slots=slots, segment=segment)
    for r in (rows_a[:1] + rows_b[:1] + rows_a[1:3] + rows_b[1:3]):
        m = dense_store.route(r)
        out = (dense_eng.generate(np.asarray(r[m:], np.int32),
                                  max_new_tokens=n_new,
                                  prefix=np.asarray(r[:m], np.int32))
               if m > 0 else dense_eng.generate(r, max_new_tokens=n_new))
        assert np.array_equal(out, refs[tuple(r)]), "dense parity broke"
    dense_st = dense_store.stats()
    assert dense_st["assembly_bytes_peak"] > 0, (
        "expected the dense path to re-assemble under prefix-entry "
        f"rotation: {dense_st}")

    # -- capacity under a fixed HBM budget -----------------------------------
    # budget = exactly what the dense engine allocates (slots x window);
    # a window-bound allocator can hold `slots` rows in it, full stop.
    cap_pool = PagePool(n_pages=slots * window_pages_budget + 1,
                        page=page,
                        page_bytes=page_kv_bytes(cfg, page))
    cap_rng = np.random.default_rng(7)
    admitted = 0
    try:
        while True:
            tokens = int(cap_rng.integers(page, cfg.max_len // 2))
            cap_pool.alloc(-(-tokens // page), tokens=tokens)
            admitted += 1
    except PagesExhausted:
        pass
    cap_pool.check_invariants()
    margin = admitted / slots
    if admitted <= slots:
        raise AssertionError(
            f"paged admission ({admitted} rows) not better than "
            f"window-bound ({slots}) for the mixed-length workload")
    print(f"# capacity: {admitted} mixed-length rows vs {slots} "
          f"window-bound in the same HBM budget ({margin:.2f}x)",
          file=sys.stderr)

    if max_ref_seen <= 1:
        # polling is best-effort on a fast machine; the deterministic
        # proof: acquire the shared prefix directly on a fresh paged
        # store ref + the store's own ref = refcount 2
        eng, store, pool = mk_paged(1)
        routed(eng, store, rows_a[0])
        acq = store.acquire_pages(rows_a[0][:store._target_len(
            len(rows_a[0]))])
        assert acq is not None
        max_ref_seen = pool.stats()["max_refcount"]
        pool.release(acq[0])
        assert max_ref_seen > 1, "shared prefix pages never shared"

    return {
        "mode": "paged",
        "platform": jax.devices()[0].platform,
        "n_requests": n_requests,
        "prefix_len": prefix_len,
        "n_new": n_new,
        "slots": slots,
        "page_tokens": page,
        "parity_rows_checked": parity_checked,
        "parity": True,
        "depths": {str(d): v for d, v in per_depth.items()},
        "dense_assembly_bytes_peak": dense_st["assembly_bytes_peak"],
        "dense_assemblies": dense_st["assemblies"],
        "paged_assembly_bytes_peak": 0,
        "assembly_bytes_eliminated_per_hit":
            dense_st["assembly_bytes_peak"],
        "max_shared_refcount_observed": max_ref_seen,
        "capacity_rows_paged": admitted,
        "capacity_rows_window_bound": slots,
        "capacity_margin": round(margin, 3),
    }


def _sim_tokens_per_step(prompt, emitted, kb: int, ngram_max: int = 3):
    """Host-side replay of the engine's accept rule over a KNOWN chain:
    how many tokens/step prompt-lookup drafting would verify if the
    model emits ``emitted`` after ``prompt``. Used to pick genuinely
    repetitive-continuation prompts for the throughput claim (a
    random-init tiny model's greedy decode falls into cycles, but not
    every prompt's cycle is lookup-friendly)."""
    from lambdipy_tpu.models.llama import _lookup_draft

    pos, steps = 0, 0
    while pos < len(emitted):
        ctx = list(prompt) + list(emitted[: pos + 1])  # incl. pending
        d = _lookup_draft(ctx, kb, ngram_max=ngram_max)[: kb - 1]
        m = 0
        while (m < kb - 1 and pos + 1 + m < len(emitted)
               and d[m] == emitted[pos + 1 + m]):
            m += 1
        pos += m + 1
        steps += 1
    return len(emitted) / max(1, steps)


def spec_record(*, n_requests: int = 3, n_new: int = 64, k: int = 8,
                segment: int = 8, slots: int = 4, block: int = 32,
                depths=(1, 2), reps: int = 3,
                extra: dict | None = None) -> dict:
    """Engine speculative-decoding sweep (CPU-runnable), gating the two
    claims the spec_k knob makes:

    1. BITWISE PARITY spec-on-vs-off — greedy AND seeded-sampled, cold
       rows and prefix-cache hits, streamed and non-streamed, under
       concurrent traffic, at pipeline depths 1 and 2, dense AND paged
       (--kv-paged's engine): the speculative engine's tokens equal the
       solo server's (and therefore the plain engine's, which the
       pipeline/paged sweeps already tie to solo) exactly. Acceptance
       is chain-deterministic, so this holds at ANY acceptance rate —
       the accept-all workload below is where it also pays.
    2. THROUGHPUT — on a repetitive-continuation workload in the
       accept-all regime (prompts shifted past their greedy decode's
       transient so the model's own attractor cycle sits in-context
       for prompt lookup), the speculative engine beats the plain
       engine by > 1.5x tok/s, with acceptance rate and tokens/step
       published through the engine's ``batching.spec`` /metrics block
       (asserted > 1 token per weight read). The throughput model is
       LARGER than the parity model (hidden 512 x 3 layers): at tiny
       dims the weights sit in cache and the weight-read amortization
       that speculation exists to exploit is invisible — the bigger
       model reproduces the weight-bytes-bound decode regime at CPU
       scale. Walls are measured over multiple request rounds through
       one live engine, interleaved best-of-N, because sub-second
       engine walls on a shared CPU are scheduler-noise-bound."""
    from concurrent.futures import ThreadPoolExecutor

    import numpy as np

    import jax

    from lambdipy_tpu.models import registry
    from lambdipy_tpu.models.llama import init_page_arena, page_kv_bytes
    from lambdipy_tpu.runtime.continuous import ContinuousBatcher
    from lambdipy_tpu.runtime.metrics import SpecDecodeStats
    from lambdipy_tpu.runtime.pagepool import PagePool, page_width
    from lambdipy_tpu.runtime.prefixstore import PrefixStore

    dims = {"vocab_size": 2048, "hidden": 128, "layers": 2, "heads": 4,
            "kv_heads": 2, "mlp": 256, "max_len": 512}
    dims.update(extra or {})
    adapter = registry.get("llama3-8b").build(dtype="float32", extra=dims)
    cfg = adapter.config
    params = jax.device_put(adapter.init_params(seed=0))
    server = adapter.make_server(params, prefix_cache_max=2)

    # -- workload selection: repetitive-continuation prompts ----------------
    rng = np.random.default_rng(0)
    pool_prompts = [rng.integers(1, cfg.vocab_size, 4).tolist()
                    for _ in range(20)]
    # cyclic prompts nudge the random-init model's greedy decode into a
    # lookup-friendly cycle from token 0 (the templated-output shape)
    for _ in range(8):
        pat = rng.integers(1, cfg.vocab_size, 3).tolist()
        pool_prompts.append(pat * 3)
    scored = []
    for p in pool_prompts:
        ref = server.generate(p, max_new_tokens=n_new)
        scored.append((_sim_tokens_per_step(p, ref[0].tolist(), k), p, ref))
    scored.sort(key=lambda t: -t[0])
    rows = [p for _, p, _ in scored[:n_requests]]
    refs = {tuple(p): r for _, p, r in scored}
    sim_tps = round(scored[0][0], 2)  # parity legs don't need repeats;
    # the throughput section below gates the accept-all premise
    sample_kw = dict(temperature=0.8, top_k=32, seed=11)
    refs_s = {tuple(p): server.generate(p, max_new_tokens=n_new,
                                        **sample_kw) for p in rows}
    # a shared-prefix pair for the prefix-hit parity leg
    shared = rng.integers(1, cfg.vocab_size, 2 * block).tolist()
    pfx_rows = [shared + rng.integers(1, cfg.vocab_size, 4).tolist()
                for _ in range(2)]
    for r in pfx_rows:
        refs[tuple(r)] = server.generate(r, max_new_tokens=n_new)

    page = page_width(cfg.max_len, block)

    def mk_engine(spec: int, depth: int, paged: bool):
        pool = None
        store = None
        if paged:
            n_pages = slots * (cfg.max_len // page) + 1
            pool = PagePool(n_pages=n_pages, page=page,
                            page_bytes=page_kv_bytes(cfg, page),
                            make_arena=lambda n=n_pages: init_page_arena(
                                cfg, n, page))
        eng = ContinuousBatcher(server, slots=slots, segment=segment,
                                pipeline_depth=depth, page_pool=pool,
                                spec_k=spec)
        eng.spec_metrics = SpecDecodeStats()  # per-engine counters
        store = PrefixStore(server, block=block, budget_mb=64, pool=pool)
        if pool is not None:
            eng.prefix_pages_fn = store.acquire_pages
        return eng, store

    def routed(eng, store, row, sampled=False, stream=False):
        m = store.route(row)
        kw = dict(sample_kw) if sampled else {}
        pfx = np.asarray(row[:m], np.int32) if m > 0 else None
        suf = np.asarray(row[m:], np.int32) if m > 0 else row
        if stream:
            return np.concatenate(
                list(eng.generate_stream(suf, max_new_tokens=n_new,
                                         prefix=pfx, **kw)),
                axis=1)[:, :n_new]
        return eng.generate(suf, max_new_tokens=n_new, prefix=pfx, **kw)

    parity_checked = 0
    for paged in (False, True):
        for depth in sorted(set(depths)):
            for spec in (0, k):
                eng, store = mk_engine(spec, depth, paged)
                # concurrent cold greedy rows (the repetitive workload)
                with ThreadPoolExecutor(max_workers=len(rows)) as ex:
                    outs = list(ex.map(
                        lambda r: eng.generate(r, max_new_tokens=n_new),
                        rows))
                for r, o in zip(rows, outs):
                    assert np.array_equal(o, refs[tuple(r)]), (
                        f"spec={spec} depth={depth} paged={paged}: "
                        f"cold greedy parity broke")
                    parity_checked += 1
                # seeded-sampled rows
                for r in rows[:2]:
                    o = eng.generate(r, max_new_tokens=n_new, **sample_kw)
                    assert np.array_equal(o, refs_s[tuple(r)]), (
                        f"spec={spec} depth={depth} paged={paged}: "
                        "sampled parity broke")
                    parity_checked += 1
                # prefix-hit rows (cold walk then a zero-copy/dense hit)
                for r in pfx_rows:
                    o = routed(eng, store, r)
                    assert np.array_equal(o, refs[tuple(r)]), (
                        f"spec={spec} depth={depth} paged={paged}: "
                        "prefix parity broke")
                    parity_checked += 1
                # streamed hit: concatenated chunks == fused output
                o = routed(eng, store, pfx_rows[0], stream=True)
                assert np.array_equal(o, refs[tuple(pfx_rows[0])]), (
                    f"spec={spec} depth={depth} paged={paged}: "
                    "streamed parity broke")
                parity_checked += 1
                with eng._lock:
                    while eng._engine_running:
                        eng._lock.wait(0.05)
                if paged:
                    eng.pool.check_invariants()

    # -- throughput: spec-on vs spec-off on the accept-all workload ---------
    # A bigger model than the parity legs' (weights past cache size) so
    # the decode is weight-read-bound like real serving; k = 16 so each
    # verify chunk amortizes one weight pass over many tokens.
    perf_dims = {"vocab_size": 2048, "hidden": 512, "layers": 3,
                 "heads": 8, "kv_heads": 4, "mlp": 1024, "max_len": 256}
    k_perf = 2 * k
    perf_adapter = registry.get("llama3-8b").build(dtype="float32",
                                                   extra=perf_dims)
    perf_params = jax.device_put(perf_adapter.init_params(seed=0))
    perf_server = perf_adapter.make_server(perf_params)
    # workload: decode each candidate past its transient, append the
    # first `shift` emitted tokens to the prompt (greedy continuation
    # of prompt+ref[:shift] IS ref[shift:], causally), and keep the
    # candidate whose attractor is most lookup-predictable
    shift, n_perf, rounds = 48, 48, 2
    cands = [rng.integers(1, perf_dims["vocab_size"], 4).tolist()
             for _ in range(10)]
    for _ in range(4):
        pat = rng.integers(1, perf_dims["vocab_size"], 3).tolist()
        cands.append(pat * 3)
    best_p2, best_sim, best_ref = None, -1.0, None
    for p in cands:
        ref = perf_server.generate(
            p, max_new_tokens=shift + n_perf)[0].tolist()
        p2 = list(p) + ref[:shift]
        s = _sim_tokens_per_step(p2, ref[shift:], k_perf)
        if s > best_sim:
            best_p2, best_sim = p2, s
            best_ref = np.asarray([ref[shift:]])
    if best_sim < 0.75 * k_perf:
        raise AssertionError(
            f"no accept-all attractor found: best simulated tokens/step "
            f"{best_sim:.1f} of {k_perf} — the repetitive-continuation "
            "premise is broken")
    fast_rows = [list(best_p2) for _ in range(slots)]

    def timed(spec: int):
        eng = ContinuousBatcher(perf_server, slots=slots, segment=segment,
                                pipeline_depth=1, spec_k=spec)
        eng.spec_metrics = SpecDecodeStats()
        t0 = time.monotonic()
        for _ in range(rounds):
            with ThreadPoolExecutor(max_workers=slots) as ex:
                outs = list(ex.map(
                    lambda r: eng.generate(r, max_new_tokens=n_perf),
                    fast_rows))
            for o in outs:
                # the timed rows double as one more parity check
                assert np.array_equal(o, best_ref), \
                    f"throughput-leg parity broke (spec={spec})"
        wall = time.monotonic() - t0
        with eng._lock:
            while eng._engine_running:
                eng._lock.wait(0.05)
        return wall, eng.spec_metrics.report()

    timed(0)          # warm every program family off the clock
    timed(k_perf)
    walls_off, walls_on, spec_stats = [], [], None
    for _ in range(max(2, reps)):
        walls_off.append(timed(0)[0])
        wall, spec_stats = timed(k_perf)
        walls_on.append(wall)
    total = rounds * slots * n_perf
    tok_s_off = total / min(walls_off)
    tok_s_on = total / min(walls_on)
    speedup = tok_s_on / tok_s_off
    if spec_stats["tokens_per_step"] <= 1.0:
        raise AssertionError(
            f"speculation never verified >1 token/step: {spec_stats}")
    if speedup <= 1.5:
        raise AssertionError(
            f"speculative engine speedup {speedup:.2f}x <= 1.5x on the "
            f"repetitive workload (off {tok_s_off:.1f} vs on "
            f"{tok_s_on:.1f} tok/s; spec={spec_stats})")

    return {
        "mode": "spec",
        "platform": jax.devices()[0].platform,
        "n_requests": len(rows),
        "n_new": n_new,
        "k": k,
        "k_perf": k_perf,
        "segment": segment,
        "parity_rows_checked": parity_checked,
        "parity": True,
        "sim_tokens_per_step_parity_best": sim_tps,
        "sim_tokens_per_step_perf": round(best_sim, 2),
        "engine_tok_s_spec_off": round(tok_s_off, 1),
        "engine_tok_s_spec_on": round(tok_s_on, 1),
        "speedup": round(speedup, 3),
        "acceptance_rate": spec_stats["acceptance_rate"],
        "tokens_per_step": spec_stats["tokens_per_step"],
        "draft_hit_rate": spec_stats["draft_hit_rate"],
        "wasted_verify_tokens": spec_stats["wasted_verify_tokens"],
        "tokens_per_step_hist": spec_stats["tokens_per_step_hist"],
    }


def _damp_deep_layers(params, factor: float):
    """Scale the residual-write projections (``o_proj``/``down_proj``)
    of every layer past the first by ``factor``. The damped model's
    exit-1 shallow head mostly agrees with its full forward — the
    random-init stand-in for a TRAINED self-drafting head (a real
    deployment earns that agreement by distillation; the bench buys it
    structurally) — while a full weight pass still costs ``layers`` x
    the shallow pass, which is the regime the draft tier exists to
    exploit. Works on float and int8 trees alike: scaling the f32
    ``scale`` leaf scales the effective int8 weight."""
    import re

    import jax.tree_util as jtu

    def fn(kp, leaf):
        ks = jtu.keystr(kp)
        m = re.search(r"layer_(\d+)", ks)
        if (m and int(m.group(1)) > 0 and "scale" in ks
                and ("o_proj" in ks or "down_proj" in ks)):
            return leaf * factor
        return leaf

    return jtu.tree_map_with_path(fn, params)


def _sim_draft_agreement(adapter, params, prompt, emitted):
    """Teacher-forced exit-1-vs-full argmax agreement along a known
    chain: the fraction of positions where the shallow head's greedy
    pick equals the full model's. The model-draft throughput premise
    ('the trained head usually agrees') is asserted on this number, not
    assumed."""
    import jax.numpy as jnp

    chain = list(prompt) + list(emitted)
    toks = jnp.asarray([chain], jnp.int32)
    s = len(prompt)
    full = jnp.argmax(
        adapter.module.apply(params, toks)[0][0, s - 1:-1]
        .astype(jnp.float32), -1)
    shallow = jnp.argmax(
        adapter.module.apply(params, toks, exit_layer=1)[0][0, s - 1:-1]
        .astype(jnp.float32), -1)
    return float((full == shallow).mean())


def spec_draft_record(*, n_new: int = 16, n_perf: int = 48,
                      n_adv: int = 128, k: int = 8,
                      segment: int = 8, slots: int = 4, block: int = 32,
                      reps: int = 3, extra: dict | None = None) -> dict:
    """Model-draft speculative tier sweep (CPU-runnable over 2 forced
    host devices — run via ``bench.py --spec-draft``, whose entry point
    forces ``--xla_force_host_platform_device_count=2`` before jax
    initializes), gating the claims the draft tier makes on top of the
    PR-9 lookup tier:

    1. BITWISE PARITY draft-on-vs-off — greedy AND seeded-sampled,
       streamed, under concurrent traffic, pipeline depths 1 and 2,
       dense AND paged AND tp=2 mesh: the shallow-exit drafting engine's
       tokens equal the solo server's exactly. Acceptance is
       chain-deterministic (:func:`_spec_chain_verify` scores drafts
       against the target's own select walk), so this holds at ANY
       acceptance rate; an ``aux`` leg runs the same contract through
       the host-side :class:`DraftProvider` seam with a
       ``registry.draft_twin`` server.
    2. THROUGHPUT on a NON-repetitive workload — prompts are SELECTED
       for minimal prompt-lookup predictability (simulated lookup
       tokens/step < 2 of ``k``, asserted), i.e. exactly the chat-shaped
       traffic where the PR-9 lookup tier pays nothing, and the
       model-draft engine must beat spec-off by > 1.5x tok/s. The
       throughput model is deep (hidden 512 x 8 layers, weights past
       cache size) with later layers damped (:func:`_damp_deep_layers`)
       so the exit-1 head mostly agrees with the full model — the
       teacher-forced agreement is measured and asserted >= 0.9, the
       honest stand-in for a trained head.
    3. PER-ROW ADAPTIVE k — on the easy workload the acceptance EWMA
       must steer rows from the k=2 slow-start up to the full bucket
       (k-hist dominated by ``k``, model acceptance EWMA >= 0.75); on an
       ADVERSARIAL workload (high-temperature seeded-sampled rows, where
       a greedy draft is near-noise) rows must demote model -> lookup ->
       off (fallback counters asserted), every verify dispatch must stay
       in the k=2 slow-start bucket, and wall-clock must hold >= 0.95x
       spec-off — the never-pay-the-draft-forward guarantee.

    Walls are interleaved best-of-N through live engines, like
    :func:`spec_record`, because sub-second engine walls on a shared CPU
    are scheduler-noise-bound."""
    from concurrent.futures import ThreadPoolExecutor

    import numpy as np

    import jax

    from lambdipy_tpu.models import registry
    from lambdipy_tpu.models.llama import init_page_arena, page_kv_bytes
    from lambdipy_tpu.parallel.mesh import make_mesh, use_mesh
    from lambdipy_tpu.parallel.sharding import shard_params
    from lambdipy_tpu.runtime.continuous import (AuxModelDraft,
                                                 ContinuousBatcher)
    from lambdipy_tpu.runtime.metrics import SpecDecodeStats
    from lambdipy_tpu.runtime.pagepool import PagePool, page_width

    if len(jax.devices()) < 2:
        raise AssertionError(
            "spec-draft sweep needs >= 2 devices for its mesh leg (run "
            "via bench.py --spec-draft, which forces 2 host devices)")

    damp = 1e-3

    # -- parity matrix: small model, dense + paged + mesh -------------------
    dims = {"vocab_size": 2048, "hidden": 128, "layers": 2, "heads": 4,
            "kv_heads": 2, "mlp": 256, "max_len": 256}
    dims.update(extra or {})
    adapter = registry.get("llama3-8b").build(dtype="float32", extra=dims)
    cfg = adapter.config
    host_params = _damp_deep_layers(adapter.init_params(seed=0), damp)
    server = adapter.make_server(jax.device_put(host_params))

    rng = np.random.default_rng(0)
    rows = [rng.integers(1, cfg.vocab_size, 4 + i).tolist()
            for i in range(3)]
    sample_kw = dict(temperature=0.8, top_k=32, seed=11)
    refs = {tuple(p): server.generate(p, max_new_tokens=n_new)
            for p in rows}
    refs_s = {tuple(p): server.generate(p, max_new_tokens=n_new,
                                        **sample_kw) for p in rows}

    mesh = make_mesh({"tp": 2}, devices=jax.devices()[:2])
    with use_mesh(mesh):
        tp_params = shard_params(host_params, mesh, adapter.tp_rules)
    tp_server = adapter.make_server(tp_params, mesh=mesh)
    page = page_width(cfg.max_len, block)

    def mk_engine(server_, paged: bool, depth: int, srv_mesh, **ekw):
        pool = None
        if paged:
            n_pages = slots * (cfg.max_len // page) + 1
            pool = PagePool(
                n_pages=n_pages, page=page,
                page_bytes=page_kv_bytes(cfg, page),
                make_arena=lambda n=n_pages, m=srv_mesh: init_page_arena(
                    cfg, n, page, mesh=m))
        eng = ContinuousBatcher(server_, slots=slots, segment=segment,
                                pipeline_depth=depth, page_pool=pool,
                                spec_k=k, **ekw)
        eng.spec_metrics = SpecDecodeStats()
        return eng

    def drain(eng):
        with eng._lock:
            while eng._engine_running:
                eng._lock.wait(0.05)

    parity_checked = 0
    legs = ([(server, paged, depth, None, "model")
             for paged in (False, True) for depth in (1, 2)]
            + [(tp_server, paged, 2, mesh, "model")
               for paged in (False, True)]
            + [(server, False, 1, None, "aux")])
    for server_, paged, depth, srv_mesh, mode in legs:
        ekw = dict(draft_mode=mode)
        if mode == "aux":
            ekw["draft_provider"] = AuxModelDraft(
                registry.draft_twin(adapter, layers=1))
        eng = mk_engine(server_, paged, depth, srv_mesh, **ekw)
        with ThreadPoolExecutor(max_workers=len(rows)) as ex:
            outs = list(ex.map(
                lambda r: eng.generate(r, max_new_tokens=n_new), rows))
        for r, o in zip(rows, outs):
            assert np.array_equal(o, refs[tuple(r)]), (
                f"mode={mode} depth={depth} paged={paged} "
                f"mesh={srv_mesh is not None}: cold greedy parity broke")
            parity_checked += 1
        for r in rows[:2]:
            o = eng.generate(r, max_new_tokens=n_new, **sample_kw)
            assert np.array_equal(o, refs_s[tuple(r)]), (
                f"mode={mode} depth={depth} paged={paged} "
                f"mesh={srv_mesh is not None}: sampled parity broke")
            parity_checked += 1
        o = np.concatenate(
            list(eng.generate_stream(rows[0], max_new_tokens=n_new)),
            axis=1)[:, :n_new]
        assert np.array_equal(o, refs[tuple(rows[0])]), (
            f"mode={mode} depth={depth} paged={paged}: streamed parity "
            "broke")
        parity_checked += 1
        drain(eng)
        if paged:
            eng.pool.check_invariants()

    # -- throughput: model-draft vs spec-off on a NON-repetitive workload ---
    perf_dims = {"vocab_size": 2048, "hidden": 512, "layers": 8,
                 "heads": 8, "kv_heads": 4, "mlp": 1024, "max_len": 256}
    perf_adapter = registry.get("llama3-8b").build(dtype="float32",
                                                   extra=perf_dims)
    perf_params = jax.device_put(
        _damp_deep_layers(perf_adapter.init_params(seed=0), damp))
    perf_server = perf_adapter.make_server(perf_params)

    cands = [rng.integers(1, perf_dims["vocab_size"], 6).tolist()
             for _ in range(8)]
    scored = []
    for p in cands:
        ref = perf_server.generate(p, max_new_tokens=n_perf)
        sim = _sim_tokens_per_step(p, ref[0].tolist(), k)
        agree = _sim_draft_agreement(perf_adapter, perf_params, p,
                                     ref[0].tolist())
        scored.append((agree, sim, p, ref))
    scored.sort(key=lambda t: (-t[0], t[1]))
    fast_rows = [p for _, _, p, _ in scored[:slots]]
    perf_refs = {tuple(p): r for _, _, p, r in scored}
    lookup_sims = [round(s, 2) for _, s, p, _ in scored
                   if tuple(p) in {tuple(q) for q in fast_rows}]
    agreement = round(min(a for a, _, p, _ in scored
                          if tuple(p) in {tuple(q) for q in fast_rows}), 3)
    if max(lookup_sims) >= 2.0:
        raise AssertionError(
            f"workload is lookup-predictable (sim tokens/step "
            f"{lookup_sims}) — the non-repetitive premise is broken")
    if agreement < 0.9:
        raise AssertionError(
            f"shallow head agreement {agreement} < 0.9 — the damped "
            "trained-head stand-in premise is broken")

    def timed(spec: int, mode: str, rows_, refs_, rounds: int = 2,
              n_tok: int = n_perf, **gen_kw):
        eng = ContinuousBatcher(perf_server, slots=slots, segment=segment,
                                pipeline_depth=1, spec_k=spec,
                                draft_mode=mode)
        eng.spec_metrics = SpecDecodeStats()
        t0 = time.monotonic()
        for _ in range(rounds):
            with ThreadPoolExecutor(max_workers=slots) as ex:
                outs = list(ex.map(
                    lambda a: eng.generate(
                        a[1], max_new_tokens=n_tok,
                        **{kk: (vv[a[0]] if isinstance(vv, list) else vv)
                           for kk, vv in gen_kw.items()}),
                    list(enumerate(rows_))))
            for r, o in zip(rows_, outs):
                assert np.array_equal(o, refs_[tuple(r)]), (
                    f"throughput-leg parity broke (spec={spec}, "
                    f"mode={mode})")
        wall = time.monotonic() - t0
        drain(eng)
        return wall, eng.spec_metrics.report()

    timed(0, "lookup", fast_rows, perf_refs)  # warm off the clock
    timed(k, "model", fast_rows, perf_refs)
    walls_off, walls_on, draft_stats = [], [], None
    for _ in range(max(2, reps)):
        walls_off.append(timed(0, "lookup", fast_rows, perf_refs)[0])
        wall, draft_stats = timed(k, "model", fast_rows, perf_refs)
        walls_on.append(wall)
    total = 2 * slots * n_perf
    tok_s_off = total / min(walls_off)
    tok_s_on = total / min(walls_on)
    speedup = tok_s_on / tok_s_off
    prov = draft_stats["draft"]["providers"].get("model") or {}
    k_hist = draft_stats["draft"]["k_hist"]
    k_steps = sum(k_hist.values())
    if speedup <= 1.5:
        raise AssertionError(
            f"model-draft speedup {speedup:.2f}x <= 1.5x on the "
            f"non-repetitive workload (off {tok_s_off:.1f} vs on "
            f"{tok_s_on:.1f} tok/s; draft={draft_stats['draft']})")
    if draft_stats["tokens_per_step"] <= 1.0:
        raise AssertionError(
            f"model drafting never verified >1 token/step: {draft_stats}")
    if prov.get("acceptance_ewma", 0.0) < 0.75:
        raise AssertionError(
            f"model acceptance EWMA {prov.get('acceptance_ewma')} < 0.75 "
            "— adaptive k cannot have converged upward")
    if k_hist.get(str(k), 0) < 0.4 * max(1, k_steps):
        raise AssertionError(
            f"adaptive k never converged to the k={k} bucket on the easy "
            f"workload: k_hist={k_hist}")

    # -- adversarial: high-temperature sampled rows must fall back ----------
    # Longer requests than the easy leg (``n_adv``): the fallback cost
    # is a BOUNDED per-admission transient (two k=2 slow-start verify
    # steps before the row demotes to off and the batch redispatches as
    # the plain segment program), so the honest question is whether it
    # amortizes over a realistic decode length — not whether two wasted
    # dispatches are visible inside a 48-token sprint.
    adv_kw = dict(temperature=[1.5 + 0.1 * i for i in range(slots)],
                  seed=[101 + i for i in range(slots)])
    adv_rows = fast_rows
    adv_refs = {}
    for i, p in enumerate(adv_rows):
        adv_refs[tuple(p)] = perf_server.generate(
            p, max_new_tokens=n_adv, temperature=adv_kw["temperature"][i],
            seed=adv_kw["seed"][i])
    timed(0, "lookup", adv_rows, adv_refs, n_tok=n_adv, **adv_kw)  # warm
    timed(k, "model", adv_rows, adv_refs, n_tok=n_adv, **adv_kw)
    adv_off, adv_on, adv_stats = [], [], None
    for _ in range(max(2, reps)):
        adv_off.append(timed(0, "lookup", adv_rows, adv_refs,
                             n_tok=n_adv, **adv_kw)[0])
        wall, adv_stats = timed(k, "model", adv_rows, adv_refs,
                                n_tok=n_adv, **adv_kw)
        adv_on.append(wall)
    adv_ratio = min(adv_off) / min(adv_on)
    fallbacks = adv_stats["draft"]["fallbacks"]
    if adv_ratio < 0.95:
        raise AssertionError(
            f"adversarial rows paid the draft forward: spec-off/draft-on "
            f"wall ratio {adv_ratio:.2f} < 0.95 (draft="
            f"{adv_stats['draft']})")
    if not fallbacks.get("model->lookup") or not fallbacks.get(
            "lookup->off"):
        raise AssertionError(
            f"adversarial rows never walked the fallback ladder: "
            f"fallbacks={fallbacks}")
    if set(adv_stats["draft"]["k_hist"]) - {"2"}:
        raise AssertionError(
            f"adversarial dispatches escaped the k=2 slow-start bucket: "
            f"k_hist={adv_stats['draft']['k_hist']}")

    return {
        "mode": "spec_draft",
        "platform": jax.devices()[0].platform,
        "n_new": n_new,
        "n_perf": n_perf,
        "k": k,
        "segment": segment,
        "parity_rows_checked": parity_checked,
        "parity": True,
        "lookup_sim_tokens_per_step": lookup_sims,
        "shallow_agreement": agreement,
        "engine_tok_s_spec_off": round(tok_s_off, 1),
        "engine_tok_s_draft_on": round(tok_s_on, 1),
        "speedup": round(speedup, 3),
        "acceptance_rate": draft_stats["acceptance_rate"],
        "tokens_per_step": draft_stats["tokens_per_step"],
        "model_acceptance_ewma": prov.get("acceptance_ewma"),
        "k_hist": k_hist,
        "adversarial_wall_ratio": round(adv_ratio, 3),
        "adversarial_fallbacks": fallbacks,
    }


def mesh_record(*, n_requests: int = 3, n_new: int = 16, segment: int = 4,
                slots: int = 4, block: int = 32, depths=(1, 2),
                reps: int = 2, extra: dict | None = None) -> dict:
    """Tensor-parallel sharded-serving sweep (CPU-runnable over 2 host
    devices — run it via ``bench.py --mesh``, whose entry point forces
    ``--xla_force_host_platform_device_count=2`` BEFORE jax first
    initializes; calling this function from a process whose jax already
    sees one device raises rather than measuring nothing), gating the
    two claims the ``mesh`` knob makes:

    1. BITWISE PARITY tp=2 vs tp=1 — greedy AND seeded-sampled, cold
       rows and prefix-cache hits (cold walk + zero-copy/dense hit),
       streamed, under concurrent traffic, at pipeline depths 1 and 2,
       dense AND paged: the sharded engine's tokens equal the
       single-device server's exactly. The Megatron TP layout shards
       output channels, so per-output reductions keep their order and
       the collectives XLA inserts reproduce the unsharded arithmetic.
    2. PER-DEVICE HBM — the engine's KV residency (B-slot carry dense,
       page arena paged) and the params each cost <= 0.55x their
       replicated footprint per device on the tp=2 mesh, read from the
       LIVE ``batching.mesh`` gauges after serving traffic (so a
       segment program silently resharding the carry back to
       replicated would fail the gate, not just the init-time claim).

    tok/s for tp=1 vs tp=2 is REPORTED, not gated: at tiny CPU dims the
    per-layer collectives dominate and tp=2 is expected slower — the
    mesh pays off where BENCH_r04 lives (8B at >0.8 single-chip HBM
    util), and what this sweep pins down is correctness + the HBM
    split that makes those deployments possible at all."""
    from concurrent.futures import ThreadPoolExecutor

    import numpy as np

    import jax

    from lambdipy_tpu.models import registry
    from lambdipy_tpu.models.llama import init_page_arena, page_kv_bytes
    from lambdipy_tpu.parallel.mesh import make_mesh, use_mesh
    from lambdipy_tpu.parallel.sharding import shard_params
    from lambdipy_tpu.runtime.continuous import ContinuousBatcher
    from lambdipy_tpu.runtime.pagepool import PagePool, page_width
    from lambdipy_tpu.runtime.prefixstore import PrefixStore

    if len(jax.devices()) < 2:
        raise AssertionError(
            "mesh sweep needs >= 2 devices (run via bench.py --mesh, "
            "which forces 2 host devices)")

    dims = {"vocab_size": 2048, "hidden": 128, "layers": 2, "heads": 4,
            "kv_heads": 2, "mlp": 256, "max_len": 256}
    dims.update(extra or {})
    adapter = registry.get("llama3-8b").build(dtype="float32", extra=dims)
    cfg = adapter.config
    host_params = adapter.init_params(seed=0)
    ref_server = adapter.make_server(jax.device_put(host_params),
                                     prefix_cache_max=2)

    rng = np.random.default_rng(0)
    rows = [rng.integers(1, cfg.vocab_size, 4 + i).tolist()
            for i in range(n_requests)]
    sample_kw = dict(temperature=0.8, top_k=32, seed=11)
    refs = {tuple(p): ref_server.generate(p, max_new_tokens=n_new)
            for p in rows}
    refs_s = {tuple(p): ref_server.generate(p, max_new_tokens=n_new,
                                            **sample_kw) for p in rows}
    shared = rng.integers(1, cfg.vocab_size, 2 * block).tolist()
    pfx_rows = [shared + rng.integers(1, cfg.vocab_size, 4).tolist()
                for _ in range(2)]
    for r in pfx_rows:
        refs[tuple(r)] = ref_server.generate(r, max_new_tokens=n_new)

    mesh = make_mesh({"tp": 2}, devices=jax.devices()[:2])
    with use_mesh(mesh):
        tp_params = shard_params(host_params, mesh, adapter.tp_rules)
    tp_server = adapter.make_server(tp_params, mesh=mesh,
                                    prefix_cache_max=2)
    page = page_width(cfg.max_len, block)

    def mk_engine(server, depth: int, paged: bool, srv_mesh):
        pool = None
        if paged:
            n_pages = slots * (cfg.max_len // page) + 1
            pool = PagePool(
                n_pages=n_pages, page=page,
                page_bytes=page_kv_bytes(cfg, page),
                make_arena=lambda n=n_pages, m=srv_mesh: init_page_arena(
                    cfg, n, page, mesh=m))
        eng = ContinuousBatcher(server, slots=slots, segment=segment,
                                pipeline_depth=depth, page_pool=pool)
        store = PrefixStore(server, block=block, budget_mb=64, pool=pool)
        if pool is not None:
            eng.prefix_pages_fn = store.acquire_pages
        return eng, store

    def routed(eng, store, row, sampled=False, stream=False):
        m = store.route(row)
        kw = dict(sample_kw) if sampled else {}
        pfx = np.asarray(row[:m], np.int32) if m > 0 else None
        suf = np.asarray(row[m:], np.int32) if m > 0 else row
        if stream:
            return np.concatenate(
                list(eng.generate_stream(suf, max_new_tokens=n_new,
                                         prefix=pfx, **kw)),
                axis=1)[:, :n_new]
        return eng.generate(suf, max_new_tokens=n_new, prefix=pfx, **kw)

    parity_checked = 0
    mesh_blocks = {}
    for paged in (False, True):
        for depth in sorted(set(depths)):
            eng, store = mk_engine(tp_server, depth, paged, mesh)
            # concurrent cold greedy rows
            with ThreadPoolExecutor(max_workers=len(rows)) as ex:
                outs = list(ex.map(
                    lambda r: eng.generate(r, max_new_tokens=n_new),
                    rows))
            for r, o in zip(rows, outs):
                assert np.array_equal(o, refs[tuple(r)]), (
                    f"tp=2 depth={depth} paged={paged}: cold greedy "
                    "parity broke")
                parity_checked += 1
            # seeded-sampled rows
            for r in rows[:2]:
                o = eng.generate(r, max_new_tokens=n_new, **sample_kw)
                assert np.array_equal(o, refs_s[tuple(r)]), (
                    f"tp=2 depth={depth} paged={paged}: sampled parity "
                    "broke")
                parity_checked += 1
            # prefix rows: cold walk, then the (zero-copy / dense) hit
            for r in pfx_rows:
                o = routed(eng, store, r)
                assert np.array_equal(o, refs[tuple(r)]), (
                    f"tp=2 depth={depth} paged={paged}: prefix parity "
                    "broke")
                parity_checked += 1
            # streamed hit: concatenated chunks == fused output
            o = routed(eng, store, pfx_rows[0], stream=True)
            assert np.array_equal(o, refs[tuple(pfx_rows[0])]), (
                f"tp=2 depth={depth} paged={paged}: streamed parity "
                "broke")
            parity_checked += 1
            with eng._lock:
                while eng._engine_running:
                    eng._lock.wait(0.05)
            stats = eng.stats()
            mb = stats.get("mesh")
            assert mb is not None and mb["segments_sharded"] > 0, stats
            # the HBM gate: live per-device KV <= 0.55x replicated
            assert mb["kv_bytes_per_device"] <= \
                0.55 * mb["kv_bytes_replicated"], (
                    f"per-device KV bytes not halved (paged={paged}): "
                    f"{mb}")
            assert mb["param_bytes_per_device"] <= \
                0.55 * mb["param_bytes_total"], mb
            mesh_blocks["paged" if paged else "dense"] = mb
            if paged:
                eng.pool.check_invariants()

    # -- throughput: tp=1 vs tp=2, reported ---------------------------------
    def timed(server):
        eng = ContinuousBatcher(server, slots=slots, segment=segment,
                                pipeline_depth=1)
        work = [list(rows[i % len(rows)]) for i in range(slots)]
        with ThreadPoolExecutor(max_workers=slots) as ex:  # warm
            list(ex.map(lambda r: eng.generate(r, max_new_tokens=n_new),
                        work))
        walls = []
        for _ in range(max(1, reps)):
            t0 = time.monotonic()
            with ThreadPoolExecutor(max_workers=slots) as ex:
                outs = list(ex.map(
                    lambda r: eng.generate(r, max_new_tokens=n_new),
                    work))
            walls.append(time.monotonic() - t0)
            for r, o in zip(work, outs):
                assert np.array_equal(o, refs[tuple(r)]), \
                    "throughput-leg parity broke"
        with eng._lock:
            while eng._engine_running:
                eng._lock.wait(0.05)
        return slots * n_new / min(walls)

    tok_s_tp1 = timed(ref_server)
    tok_s_tp2 = timed(tp_server)

    return {
        "mode": "mesh",
        "platform": jax.devices()[0].platform,
        "devices": len(jax.devices()),
        "mesh": {"tp": 2},
        "n_requests": len(rows),
        "n_new": n_new,
        "segment": segment,
        "parity_rows_checked": parity_checked,
        "parity": True,
        "kv_bytes_per_device_dense": mesh_blocks["dense"][
            "kv_bytes_per_device"],
        "kv_bytes_replicated_dense": mesh_blocks["dense"][
            "kv_bytes_replicated"],
        "hbm_savings_dense": mesh_blocks["dense"]["hbm_savings"],
        "hbm_savings_paged": mesh_blocks["paged"]["hbm_savings"],
        "param_savings": mesh_blocks["dense"]["param_savings"],
        "collectives_per_segment": mesh_blocks["dense"][
            "collectives_per_segment"],
        "engine_tok_s_tp1": round(tok_s_tp1, 1),
        "engine_tok_s_tp2": round(tok_s_tp2, 1),
        "tp2_speedup_cpu": round(tok_s_tp2 / tok_s_tp1, 3),
    }


def sp_prefill_record(*, n_new: int = 12, segment: int = 8,
                      slots: int = 4, block: int = 16,
                      walk_ms: float = 150.0, max_ratio: float = 0.6,
                      ttft_reps: int = 2,
                      multipliers=(8, 16)) -> dict:
    """Whole-prompt sequence-parallel prefill sweep (CPU-runnable over
    2 host devices — run via ``bench.py --sp-prefill``, whose entry
    point forces ``--xla_force_host_platform_device_count=2`` BEFORE
    jax initializes), gating the two claims the ``prefill_mode=sp``
    knob makes:

    1. BITWISE PARITY sp vs chunked on the SAME sp=2-mesh server —
       greedy AND seeded-sampled, cold rows and prefix-store hits
       (cold walk + hit), streamed, under concurrent traffic, dense
       AND paged, plus the long-context runner at 8x/16x the compiled
       window (the sharded round schedule vs the serial window/2
       slide chain, greedy + seeded-sampled). The sharded program
       computes each query block's online-softmax over the SAME key
       blocks in the SAME order the serial chain visits them, so the
       combine is block-exact, not approximately equal.
    2. COLD TTFT <= ``max_ratio`` x chunked — per-chunk prefill device
       time modeled through the deterministic ``prefix_walk`` delay
       site (the --disagg/--sessions idiom: real tiny-model prefill is
       too cheap on CPU to carry a latency claim). A 6-chunk cold walk
       pays 6 modeled chunk-times serially but only ceil(6/sp)=3
       round-times sharded: the sp walk stacks sp chunks of device
       time onto one critical-path slot.

    tok/s is NOT gated: at tiny CPU dims the per-round collectives
    dominate. What this sweep pins down is correctness plus the
    critical-path contraction that makes sp prefill pay off where the
    real deployments live."""
    from concurrent.futures import ThreadPoolExecutor

    import numpy as np

    import jax

    from lambdipy_tpu.models import registry
    from lambdipy_tpu.models.llama import init_page_arena, page_kv_bytes
    from lambdipy_tpu.parallel.mesh import make_mesh, use_mesh
    from lambdipy_tpu.parallel.sharding import shard_params
    from lambdipy_tpu.runtime.continuous import ContinuousBatcher
    from lambdipy_tpu.runtime.faults import FaultPlan
    from lambdipy_tpu.runtime.longctx import LongContextRunner
    from lambdipy_tpu.runtime.metrics import PrefillStats
    from lambdipy_tpu.runtime.pagepool import PagePool, page_width
    from lambdipy_tpu.runtime.prefixstore import PrefixStore

    if len(jax.devices()) < 2:
        raise AssertionError(
            "sp-prefill sweep needs >= 2 devices (run via bench.py "
            "--sp-prefill, which forces 2 host devices)")

    adapter = registry.get("llama-tiny").build()
    cfg = adapter.config
    host_params = adapter.init_params(seed=0)
    mesh = make_mesh({"sp": 2}, devices=jax.devices()[:2])
    with use_mesh(mesh):
        sp_params = shard_params(host_params, mesh, adapter.tp_rules)
    server = adapter.make_server(sp_params, mesh=mesh,
                                 prefill_chunk=block)
    page = page_width(cfg.max_len, block)

    rng = np.random.default_rng(0)
    rows = [rng.integers(1, cfg.vocab_size, n).tolist()
            for n in (24, 40, 96)]
    sample_kw = dict(temperature=0.8, top_k=32, seed=11)
    shared = rng.integers(1, cfg.vocab_size, 2 * block).tolist()
    pfx_rows = [shared + rng.integers(1, cfg.vocab_size, 4).tolist()
                for _ in range(2)]

    def mk_engine(mode: str, paged: bool):
        pool = None
        if paged:
            n_pages = slots * (cfg.max_len // page) + 1
            pool = PagePool(
                n_pages=n_pages, page=page,
                page_bytes=page_kv_bytes(cfg, page),
                make_arena=lambda n=n_pages: init_page_arena(
                    cfg, n, page, mesh=mesh))
        eng = ContinuousBatcher(server, slots=slots, segment=segment,
                                page_pool=pool, prefill_mode=mode)
        store = PrefixStore(server, block=block, budget_mb=64,
                            pool=pool, prefill_mode=mode,
                            prefill_stats=eng.prefill_stats)
        if pool is not None:
            eng.prefix_pages_fn = store.acquire_pages
        return eng, store

    def routed(eng, store, row, sampled=False, stream=False):
        m = store.route(row)
        kw = dict(sample_kw) if sampled else {}
        pfx = np.asarray(row[:m], np.int32) if m > 0 else None
        suf = np.asarray(row[m:], np.int32) if m > 0 else row
        if stream:
            return np.concatenate(
                list(eng.generate_stream(suf, max_new_tokens=n_new,
                                         prefix=pfx, **kw)),
                axis=1)[:, :n_new]
        return eng.generate(suf, max_new_tokens=n_new, prefix=pfx, **kw)

    def drain(eng):
        with eng._lock:
            while eng._engine_running:
                eng._lock.wait(0.05)

    parity_checked = 0
    sharded_chunks = 0
    for paged in (False, True):
        ceng, cstore = mk_engine("chunked", paged)
        seng, sstore = mk_engine("sp", paged)
        assert seng.prefill_sp == 2, "sp engine failed to see the mesh"
        # concurrent cold greedy rows: chunked engine is the reference
        with ThreadPoolExecutor(max_workers=len(rows)) as ex:
            refs = list(ex.map(
                lambda r: ceng.generate(r, max_new_tokens=n_new), rows))
        with ThreadPoolExecutor(max_workers=len(rows)) as ex:
            outs = list(ex.map(
                lambda r: seng.generate(r, max_new_tokens=n_new), rows))
        for r, ref, o in zip(rows, refs, outs):
            assert np.array_equal(o, ref), (
                f"paged={paged}: sp cold greedy parity broke "
                f"(len={len(r)})")
            parity_checked += 1
        # seeded-sampled rows
        for r in rows[:2]:
            ref = ceng.generate(r, max_new_tokens=n_new, **sample_kw)
            o = seng.generate(r, max_new_tokens=n_new, **sample_kw)
            assert np.array_equal(o, ref), (
                f"paged={paged}: sp sampled parity broke")
            parity_checked += 1
        # prefix rows: each store walks its mode's cold walk, then hits
        for r in pfx_rows:
            ref = routed(ceng, cstore, r)
            o = routed(seng, sstore, r)
            assert np.array_equal(o, ref), (
                f"paged={paged}: sp prefix parity broke")
            parity_checked += 1
        # streamed hit: concatenated chunks == fused output
        ref = routed(ceng, cstore, pfx_rows[0], stream=True)
        o = routed(seng, sstore, pfx_rows[0], stream=True)
        assert np.array_equal(o, ref), (
            f"paged={paged}: sp streamed parity broke")
        parity_checked += 1
        drain(ceng)
        drain(seng)
        rep = seng.stats()["prefill"]
        assert rep["mode"] == "sp" and rep["sp"] == 2, rep
        assert rep["sharded_chunks"] > 0, (
            f"paged={paged}: the sp engine never sharded a prefill: "
            f"{rep}")
        sharded_chunks += rep["sharded_chunks"]
        if paged:
            seng.pool.check_invariants()
            ceng.pool.check_invariants()

    # -- long-context: sp rounds vs the serial window/2 slide chain ---------
    window = 64
    lc_checked = 0
    for mult in multipliers:
        s = mult * window - 32

        def mk_pool(extra=0):
            n_pages = 2 * (cfg.max_len // page) + 1 + extra
            return PagePool(n_pages=n_pages, page=page,
                            page_bytes=page_kv_bytes(cfg, page),
                            make_arena=lambda n=n_pages: init_page_arena(
                                cfg, n, page, mesh=mesh))

        row = rng.integers(1, cfg.vocab_size, s).tolist()
        kw = dict(window=window, segment=segment,
                  max_logical_ctx=mult * window)
        for knobs in (dict(temperature=0.0),
                      dict(temperature=0.8, top_k=20, seed=5)):
            serial = LongContextRunner(server, mk_pool(), **kw).generate(
                row, max_new_tokens=8, **knobs)
            stats = PrefillStats()
            stats.configure("sp", 2)
            sp_pool = mk_pool(extra=4)
            sharded = LongContextRunner(
                server, sp_pool, prefill_mode="sp",
                prefill_stats=stats, **kw).generate(
                row, max_new_tokens=8, **knobs)
            assert np.array_equal(np.asarray(serial),
                                  np.asarray(sharded)), (
                f"long-context {mult}x sampled={'seed' in knobs}: sp "
                "rounds diverged from the serial slide chain")
            assert stats.report()["rounds"] == -(-s // window), \
                stats.report()
            assert sp_pool.free_count() == sp_pool.capacity_pages
            lc_checked += 1

    # -- cold TTFT: modeled per-chunk device time through prefix_walk --------
    plan = FaultPlan.from_spec(
        f"prefix_walk:delay@ms={walk_ms:g},n=inf")
    n_chunks = 6  # 96-token walk target at block=16

    def ttft(mode: str) -> float:
        eng, store = mk_engine(mode, paged=False)
        # off-the-clock warm: compile the walk + serve programs so the
        # timed runs measure modeled walk time, not first-use XLA
        warm = rng.integers(1, cfg.vocab_size, n_chunks * block + 8)
        routed(eng, store, warm.tolist())
        store.faults = plan
        best = None
        for _ in range(max(1, ttft_reps)):
            row = rng.integers(1, cfg.vocab_size,
                               n_chunks * block + 8).tolist()
            t0 = time.monotonic()
            m = store.route(row)
            assert m == n_chunks * block, (mode, m)
            gen = eng.generate_stream(
                np.asarray(row[m:], np.int32), max_new_tokens=n_new,
                prefix=np.asarray(row[:m], np.int32))
            next(gen)
            dt = time.monotonic() - t0
            list(gen)  # finish the row before the next rep
            best = dt if best is None else min(best, dt)
        drain(eng)
        rep = eng.prefill_stats.report()
        if mode == "sp":
            assert rep["rounds"] > 0 and rep["sharded_chunks"] > 0, rep
        return best

    ttft_chunked = ttft("chunked")
    ttft_sp = ttft("sp")
    ratio = ttft_sp / ttft_chunked
    assert ratio <= max_ratio, (
        f"sp cold TTFT {ttft_sp * 1e3:.0f}ms not <= {max_ratio}x "
        f"chunked {ttft_chunked * 1e3:.0f}ms at {walk_ms:g}ms/chunk "
        f"({n_chunks} chunks)")

    return {
        "mode": "sp-prefill",
        "platform": jax.devices()[0].platform,
        "devices": len(jax.devices()),
        "mesh": {"sp": 2},
        "n_new": n_new,
        "segment": segment,
        "parity_rows_checked": parity_checked,
        "long_context_runs_checked": lc_checked,
        "parity": True,
        "sharded_chunks": int(sharded_chunks),
        "walk_ms": walk_ms,
        "walk_chunks": n_chunks,
        "ttft_chunked_ms": round(ttft_chunked * 1e3, 1),
        "ttft_sp_ms": round(ttft_sp * 1e3, 1),
        "ttft_ratio": round(ratio, 3),
        "ttft_gate": max_ratio,
    }


def chaos_record(*, kinds=("exception", "delay", "hang"),
                 n_new: int = 16, segment: int = 4,
                 watchdog_s: float = 1.0, max_replays: int = 1,
                 extra: dict | None = None) -> dict:
    """Deterministic chaos matrix (CPU-runnable): every fault site x
    {exception, delay, hang} injected into a live continuous engine via
    runtime/faults.py, asserting the fault-isolation contract end to
    end — no waiter outlives its bound, zero requests are silently lost
    (each returns a result, a transparently replayed result, or an
    explicit error), and the engine serves a bitwise-clean request
    afterwards. Also asserts the REPLAY PARITY claim: a seeded-sampled
    request whose first attempt dies at an injected fault returns a
    bitwise-identical completion to the fault-free run, plus one
    permanent-hang case proving a wedged engine errors its waiters
    within the watchdog bound instead of hanging them."""
    import threading as _threading

    import numpy as np

    import jax

    from lambdipy_tpu.models import registry
    from lambdipy_tpu.runtime.continuous import ContinuousBatcher
    from lambdipy_tpu.runtime.faults import SITES, FaultPlan

    dims = {"vocab_size": 2048, "hidden": 128, "layers": 2, "heads": 4,
            "kv_heads": 2, "mlp": 256, "max_len": 128}
    dims.update(extra or {})
    adapter = registry.get("llama3-8b").build(dtype="float32", extra=dims)
    params = jax.device_put(adapter.init_params(seed=0))
    server = adapter.make_server(params)

    # one greedy + one seeded-sampled row: replay parity must hold for
    # both (the sampled row is the stronger claim — its PRNG chain must
    # restart bitwise); the prefix row exercises the prefix_assemble site
    reqs = [
        {"row": [1, 2, 3, 4], "kw": {}},
        {"row": [9, 8, 7], "kw": dict(temperature=0.8, seed=7)},
    ]
    prefix = list(range(1, 20))
    solo = [server.generate(r["row"], max_new_tokens=n_new, **r["kw"])
            for r in reqs]
    solo_pfx = server.generate(prefix + [4, 5], max_new_tokens=n_new)

    # warm every engine program this matrix can dispatch (group prefill
    # at joiner counts 1-3, pack, segment windows, prefix continuation)
    # through a fault-free engine first: the watchdog cannot tell a
    # first-use XLA compile from a wedge, and the whole point of a 1 s
    # chaos watchdog is bounding waits that are normally milliseconds
    from concurrent.futures import ThreadPoolExecutor

    warm = ContinuousBatcher(server, slots=4, segment=segment)
    with ThreadPoolExecutor(max_workers=3) as ex:
        futs = [ex.submit(warm.generate, r["row"], max_new_tokens=n_new,
                          **r["kw"]) for r in reqs]
        futs.append(ex.submit(warm.generate, [4, 5],
                              max_new_tokens=n_new, prefix=prefix))
        for f in futs:
            f.result()
    for r in reqs:  # solo joins compile the 1-row group-prefill family
        warm.generate(r["row"], max_new_tokens=n_new, **r["kw"])

    def run_case(site: str, kind: str, *, spec: str, permanent: bool):
        plan = FaultPlan.from_spec(spec)
        engine = ContinuousBatcher(server, slots=4, segment=segment,
                                   faults=plan, watchdog_s=watchdog_s,
                                   max_replays=max_replays)
        results: dict = {}

        def one(i, row, kw, pfx=None):
            try:
                results[i] = engine.generate(
                    row, max_new_tokens=n_new, prefix=pfx, **kw)
            except Exception as e:  # noqa: BLE001 — explicit error = ok
                results[i] = e

        workers = [
            _threading.Thread(target=one, args=(i, r["row"], r["kw"]),
                              daemon=True)
            for i, r in enumerate(reqs)]
        if site == "prefix_assemble":
            workers.append(_threading.Thread(
                target=one, args=(len(reqs), [4, 5], {}, prefix),
                daemon=True))
        for w in workers:
            w.start()
        # the waiter bound: injected hangs must resolve via the watchdog
        # (trip + replay or error), never by this deadline
        deadline = time.monotonic() + max(30.0, 8 * watchdog_s)
        for w in workers:
            w.join(timeout=max(0.0, deadline - time.monotonic()))
        hung = [i for i, w in enumerate(workers) if w.is_alive()]
        if hung:
            raise AssertionError(
                f"chaos {site}:{kind}: waiter(s) {hung} still blocked "
                f"past the bound — the watchdog failed its one job")
        ok = errors = 0
        refs = solo + [solo_pfx]
        for i, w in enumerate(workers):
            out = results.get(i)
            if isinstance(out, Exception):
                errors += 1
            elif out is not None and np.array_equal(out, refs[i]):
                ok += 1
            else:
                raise AssertionError(
                    f"chaos {site}:{kind}: request {i} returned WRONG "
                    f"tokens — silent corruption, worse than an error")
        if kind == "delay" and errors:
            raise AssertionError(
                f"chaos {site}:{kind}: a pure delay errored {errors} "
                f"request(s) — delays must only slow, never fail")
        plan.release()
        faults = engine.stats()["faults"]
        if not permanent:
            # the engine must serve again, bitwise, on the SAME batcher
            again = engine.generate(reqs[0]["row"], max_new_tokens=n_new)
            if not np.array_equal(again, solo[0]):
                raise AssertionError(
                    f"chaos {site}:{kind}: post-fault output diverged")
            if engine.wedged:
                raise AssertionError(
                    f"chaos {site}:{kind}: engine still wedged after a "
                    f"clean serve")
        elif errors == 0:
            raise AssertionError(
                f"chaos {site}:{kind} (permanent): every waiter "
                f"'succeeded' against a permanently hung site")
        return {"site": site, "kind": kind, "spec": spec, "ok": ok,
                "errors": errors, "faults": faults}

    cases = []
    for site in SITES:
        for kind in kinds:
            if kind == "delay":
                spec = f"{site}:delay@ms=120,n=2"
            elif kind == "exception":
                spec = f"{site}:exception@seg=1"
            else:
                # bounded hang: the watchdog trips, the replay lands on
                # the recovered site — the permanent variant runs below
                spec = f"{site}:hang@seg=1,n=1"
            cases.append(run_case(site, kind, spec=spec, permanent=False))
    # the permanent wedge: every fetch hangs forever; waiters must get
    # explicit errors within the watchdog bound and the engine must
    # report wedged on its fault surface
    perm = run_case("segment_fetch", "hang",
                    spec="segment_fetch:hang", permanent=True)
    if not perm["faults"]["wedged"]:
        raise AssertionError(
            "permanent segment_fetch hang did not wedge the engine")
    cases.append({**perm, "kind": "hang_permanent"})
    replayed = sum(c["faults"]["replays"]["succeeded"] for c in cases)
    if replayed == 0:
        raise AssertionError("no chaos case exercised a successful "
                             "replay — the matrix is vacuous")
    return {
        "mode": "chaos",
        "platform": jax.devices()[0].platform,
        "watchdog_s": watchdog_s,
        "max_replays": max_replays,
        "n_new": n_new,
        "cases": cases,
        "replays_succeeded": replayed,
        "passed": True,
    }


def chaos_fleet_record(*, replicas: int = 2, n_new: int = 6,
                       block: int = 16, prefix_len: int = 32,
                       requests: int = 8, spill_cap: int = 32) -> dict:
    """Fleet-boundary chaos matrix (CPU-runnable): a live ``replicas``-
    server fleet behind the resilient router, with the NETWORK made to
    lie through the runtime/faults.py router-side sites — dropped
    connections (``route_connect``), connections dying mid-body
    (``route_body``), latency spikes (``route_latency``), flapping
    replicas (``probe``) — plus a transient fleet-wide shed burst.

    Asserted per case, end to end: ZERO silent losses (every
    non-streamed request is either delivered BITWISE identical to the
    direct single-server reference or answered with an explicit shed
    carrying ``Retry-After``), bounded tail latency under the injected
    latency spike, full recovery after a flap (every replica routable
    again), and SPILL-QUEUE ABSORPTION — the shed-burst case must
    complete with 0 client-visible 429/503s because the router parked
    the burst in its sched-backed queue and drained it on recovery."""
    import tempfile
    import threading as _threading
    import urllib.error
    import urllib.request
    from concurrent.futures import ThreadPoolExecutor
    from pathlib import Path

    import numpy as np

    import jax

    from lambdipy_tpu.fleet import FleetRouter, ReplicaPool
    from lambdipy_tpu.runtime.faults import FaultPlan
    from lambdipy_tpu.runtime.server import BundleServer

    tmp = Path(tempfile.mkdtemp(prefix="lambdipy-chaos-fleet-"))
    bundle = _build_fleet_bundle(tmp, n_new=n_new, block=block,
                                 name="chaos-fleet")
    rng = np.random.default_rng(0)
    rows = _shared_prefix_rows(rng, n_requests=requests,
                               prefix_len=prefix_len, suffix_len=4,
                               vocab=512)

    def completion(base: str, row: list, timeout: float = 120) -> list:
        req = urllib.request.Request(
            f"{base}/v1/completions",
            data=json.dumps({"prompt": row, "max_tokens": n_new,
                             "temperature": 0}).encode(),
            headers={"Content-Type": "application/json"}, method="POST")
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return json.loads(resp.read())["choices"][0]["tokens"]

    servers = [BundleServer(bundle, warmup=False).start_background()
               for _ in range(replicas)]
    try:
        # bitwise reference + compile warm on EVERY replica (identical
        # init params -> identical outputs; warming all of them keeps
        # fault-window latencies about the fault, not about XLA)
        refs = {}
        for s in servers:
            base = f"http://127.0.0.1:{s.port}"
            for row in rows:
                out = completion(base, row)
                prev = refs.setdefault(tuple(row), out)
                if prev != out:
                    raise AssertionError(
                        "replicas disagree on identical-params greedy "
                        "decode — the parity reference is broken")

        def run_case(case: str, *, fault_spec: str | None = None,
                     during=None, fail_threshold: int = 1,
                     expect_failover: bool = False,
                     expect_spill: bool = False,
                     expect_flap: bool = False,
                     max_latency_s: float = 30.0,
                     allow_shed: bool = False) -> dict:
            plan = (FaultPlan.from_spec(fault_spec) if fault_spec
                    else FaultPlan.empty())
            pool = ReplicaPool(probe_interval=0.2,
                               fail_threshold=fail_threshold,
                               readmit_passes=2, probe_timeout=5.0,
                               faults=plan)
            for i, s in enumerate(servers):
                pool.attach(f"r{i}", f"http://127.0.0.1:{s.port}")
            pool.probe_all()
            pool.start()
            router = FleetRouter(
                pool, affinity_on=True, block=block, max_retries=3,
                backoff_s=0.02, backoff_cap_s=0.3, request_timeout=120,
                spill_cap=spill_cap, spill_max_wait_s=30.0,
                breaker_fails=4, breaker_open_s=0.5,
                retry_budget=0.5, faults=plan).start_background()
            base = f"http://127.0.0.1:{router.port}"
            timer = None
            if during is not None:
                timer = _threading.Timer(0.6, during)
                timer.start()
            delivered = sheds = 0
            silent: list[str] = []
            lat: list[float] = []

            def one(row):
                nonlocal delivered, sheds
                t0 = time.monotonic()
                try:
                    out = completion(base, row)
                    lat.append(time.monotonic() - t0)
                    if out != refs[tuple(row)]:
                        silent.append(
                            f"{case}: WRONG tokens for {row[:4]}...")
                        return
                    delivered += 1
                except urllib.error.HTTPError as e:
                    lat.append(time.monotonic() - t0)
                    body = json.loads(e.read() or b"{}")
                    hint = body.get("retry_after_s") or \
                        (body.get("error") or {}).get("retry_after_s")
                    if e.code in (429, 503, 504) and (
                            hint is not None or e.code == 504):
                        sheds += 1  # explicit, priced — not a loss
                    else:
                        silent.append(f"{case}: status {e.code} "
                                      f"without a shed contract")
                except Exception as e:  # noqa: BLE001 — a silent loss
                    lat.append(time.monotonic() - t0)
                    silent.append(f"{case}: {type(e).__name__}: {e}")

            with ThreadPoolExecutor(max_workers=4) as ex:
                list(ex.map(one, rows))
            if expect_flap:
                # the flap must BITE (an ejection lands — the traffic
                # may all complete before the first faulty probe sweep,
                # so wait for the probe clock, not the request clock)...
                deadline = time.monotonic() + 15
                while time.monotonic() < deadline and not any(
                        r.ejections for r in pool.replicas.values()):
                    time.sleep(0.05)
                # ...and then END: every replica routable again
                deadline = time.monotonic() + 30
                while time.monotonic() < deadline and \
                        len(pool.routable()) < replicas:
                    time.sleep(0.1)
                if len(pool.routable()) < replicas:
                    raise AssertionError(
                        f"chaos-fleet {case}: fleet never recovered "
                        f"from the flap")
            plan.release()
            stats = router.stats.report()
            pool_rep = pool.report()
            router.stop()
            pool.close()
            if silent:
                raise AssertionError(
                    f"chaos-fleet {case}: silent losses: {silent[:3]}")
            if not allow_shed and sheds:
                raise AssertionError(
                    f"chaos-fleet {case}: {sheds} client-visible sheds "
                    f"— the fleet boundary amplified instead of "
                    f"absorbing")
            if delivered + sheds != len(rows):
                raise AssertionError(
                    f"chaos-fleet {case}: {delivered}+{sheds} != "
                    f"{len(rows)} — a request vanished")
            if max(lat) > max_latency_s:
                raise AssertionError(
                    f"chaos-fleet {case}: tail latency {max(lat):.1f}s "
                    f"exceeded the {max_latency_s:.0f}s bound")
            if expect_failover and stats["failovers"] < 1:
                raise AssertionError(
                    f"chaos-fleet {case}: no failover recorded — the "
                    f"fault never bit")
            if expect_spill and (stats["spill"]["spilled"] < 1
                                 or stats["spill"]["drained"] < 1):
                raise AssertionError(
                    f"chaos-fleet {case}: spill queue never absorbed "
                    f"the burst (stats: {stats['spill']})")
            if expect_flap and not any(rep["ejections"] >= 1
                                       for rep in pool_rep.values()):
                raise AssertionError(
                    f"chaos-fleet {case}: no ejection recorded — the "
                    f"flap never bit")
            return {"case": case, "delivered": delivered, "sheds": sheds,
                    "p_max_s": round(max(lat), 3),
                    "failovers": stats["failovers"],
                    "retries": stats["retries"],
                    "spill": stats["spill"],
                    "ejections": {n: rep["ejections"]
                                  for n, rep in pool_rep.items()}}

        cases = [
            # dropped connections: the first 3 forwards die on the wire
            run_case("drop", fault_spec="route_connect:exception@seg=1,n=3",
                     expect_failover=True),
            # latency spike: 300 ms injected into 6 forwards — delivered,
            # with the tail bounded
            run_case("latency",
                     fault_spec="route_latency:delay@ms=300,n=6",
                     max_latency_s=20.0),
            # connection dies mid-body: the response was read but never
            # arrived intact; non-streamed, so the retry is safe
            run_case("midbody",
                     fault_spec="route_body:exception@seg=1,n=2",
                     expect_failover=True),
            # flapping replicas: probes fail (both replicas eject on
            # fail_threshold=1), then pass — traffic rides the spill
            # queue through the window and the fleet fully readmits
            run_case("flap", fault_spec="probe:exception@seg=3,n=6",
                     expect_flap=True),
        ]
        # spill absorption: a transient FLEET-WIDE shed burst (both
        # replicas draining for ~1 s). Queue capacity suffices, so the
        # acceptance bar is zero client-visible 429/503s.
        for s in servers:
            s.draining = True

        def _undrain():
            for s in servers:
                s.draining = False

        cases.append(run_case("shed_burst", during=_undrain,
                              expect_spill=True))
    finally:
        for s in servers:
            try:
                s.draining = False
                s.stop()
            except Exception:  # noqa: BLE001
                pass
    return {
        "mode": "chaos_fleet",
        "platform": jax.devices()[0].platform,
        "replicas": replicas,
        "requests": len(rows),
        "n_new": n_new,
        "spill_cap": spill_cap,
        "cases": cases,
        "passed": True,
    }


def _disagg_main() -> int:
    import argparse

    ap = argparse.ArgumentParser()
    ap.add_argument("--disagg", action="store_true")
    ap.add_argument("--block", type=int, default=64)
    ap.add_argument("--n-new", type=int, default=24)
    ap.add_argument("--parity-requests", type=int, default=6)
    ap.add_argument("--decode-window-s", type=float, default=6.0)
    ap.add_argument("--decode-new", type=int, default=64)
    ap.add_argument("--burst-len", type=int, default=449)
    ap.add_argument("--burst-requests", type=int, default=8)
    ap.add_argument("--walk-ms", type=float, default=90.0)
    ap.add_argument("--min-speedup", type=float, default=1.2)
    args = ap.parse_args()
    _enable_compile_cache()
    print(json.dumps(disagg_record(
        block=args.block, n_new=args.n_new,
        parity_requests=args.parity_requests,
        decode_window_s=args.decode_window_s,
        decode_new=args.decode_new, burst_len=args.burst_len,
        burst_requests=args.burst_requests, walk_ms=args.walk_ms,
        min_speedup=args.min_speedup)))
    return 0


def _disagg_rtt_main() -> int:
    import argparse

    ap = argparse.ArgumentParser()
    ap.add_argument("--disagg-rtt", action="store_true")
    ap.add_argument("--block", type=int, default=32)
    ap.add_argument("--max-len", type=int, default=1024)
    ap.add_argument("--chunk-ms", type=float, default=66.0)
    ap.add_argument("--walk-ms", type=float, default=66.0)
    ap.add_argument("--requests", type=int, default=3)
    ap.add_argument("--max-ratio", type=float, default=0.6)
    ap.add_argument("--ship-window", type=int, default=4)
    args = ap.parse_args()
    _enable_compile_cache()
    print(json.dumps(disagg_rtt_record(
        block=args.block, max_len=args.max_len,
        chunk_ms=args.chunk_ms, walk_ms=args.walk_ms,
        requests=args.requests, max_ratio=args.max_ratio,
        ship_window=args.ship_window)))
    return 0


def _sessions_main() -> int:
    import argparse

    ap = argparse.ArgumentParser()
    ap.add_argument("--sessions", action="store_true")
    ap.add_argument("--block", type=int, default=64)
    ap.add_argument("--first-len", type=int, default=321)
    ap.add_argument("--user-len", type=int, default=16)
    ap.add_argument("--n-new", type=int, default=24)
    ap.add_argument("--turns", type=int, default=3)
    ap.add_argument("--walk-ms", type=float, default=400.0)
    ap.add_argument("--ttft-gate", type=float, default=0.15)
    args = ap.parse_args()
    _enable_compile_cache()
    print(json.dumps(sessions_record(
        block=args.block, first_len=args.first_len,
        user_len=args.user_len, n_new=args.n_new, turns=args.turns,
        walk_ms=args.walk_ms, ttft_gate=args.ttft_gate)))
    return 0


def _autoscale_main() -> int:
    import argparse

    ap = argparse.ArgumentParser()
    ap.add_argument("--autoscale", action="store_true")
    ap.add_argument("--block", type=int, default=64)
    ap.add_argument("--burst-len", type=int, default=449)
    ap.add_argument("--walk-ms", type=float, default=90.0)
    ap.add_argument("--n-new", type=int, default=8)
    ap.add_argument("--trigger-s", type=float, default=3.5)
    ap.add_argument("--window-s", type=float, default=7.0)
    ap.add_argument("--burst-interval-ms", type=float, default=600.0)
    ap.add_argument("--probe-interval-ms", type=float, default=150.0)
    ap.add_argument("--slo-p99-ms", type=float, default=200.0)
    ap.add_argument("--max-p99-ratio", type=float, default=0.7)
    args = ap.parse_args()
    _enable_compile_cache()
    print(json.dumps(autoscale_record(
        block=args.block, burst_len=args.burst_len,
        walk_ms=args.walk_ms, n_new=args.n_new,
        trigger_s=args.trigger_s, window_s=args.window_s,
        burst_interval_ms=args.burst_interval_ms,
        probe_interval_ms=args.probe_interval_ms,
        slo_p99_ms=args.slo_p99_ms,
        max_p99_ratio=args.max_p99_ratio)))
    return 0


def _chaos_fleet_main() -> int:
    import argparse

    ap = argparse.ArgumentParser()
    ap.add_argument("--chaos-fleet", action="store_true")
    ap.add_argument("--replicas", type=int, default=2)
    ap.add_argument("--requests", type=int, default=8)
    ap.add_argument("--n-new", type=int, default=6)
    ap.add_argument("--block", type=int, default=16)
    ap.add_argument("--spill-cap", type=int, default=32)
    args = ap.parse_args()
    _enable_compile_cache()
    print(json.dumps(chaos_fleet_record(
        replicas=args.replicas, requests=args.requests, n_new=args.n_new,
        block=args.block, spill_cap=args.spill_cap)))
    return 0


def _soak_main() -> int:
    import argparse

    ap = argparse.ArgumentParser()
    ap.add_argument("--soak", action="store_true")
    ap.add_argument("--seed", type=int, action="append", default=None,
                    help="soak seed (repeatable); default: the fixed "
                         "CI set (11, 23) plus a determinism re-run")
    ap.add_argument("--soak-seconds", type=float, default=None,
                    help="window length per seed (default 22 s; longer "
                         "randomized runs use this with --seed)")
    ap.add_argument("--replay-timeline", type=str, default=None,
                    help="timeline file from a failing run: replay its "
                         "exact schedule under --seed's workload")
    ap.add_argument("--no-determinism", action="store_true")
    ap.add_argument("--autoscale", action="store_true",
                    help="run the live FleetController over the soak "
                         "fleet: its resizes join the nemesis timeline "
                         "and the zero-loss bar must hold through them")
    args = ap.parse_args()
    _enable_compile_cache()
    from lambdipy_tpu.chaos.soak import soak_record

    replay = None
    if args.replay_timeline:
        with open(args.replay_timeline) as f:
            replay = f.read()
    seeds = tuple(args.seed) if args.seed else (11, 23)
    kwargs = {}
    if args.soak_seconds:
        kwargs["duration_s"] = float(args.soak_seconds)
    # the determinism re-run is the CI default; explicit seeds/replays
    # are operator iteration loops and skip it
    determinism = (not args.no_determinism and args.seed is None
                   and replay is None)
    print(json.dumps(soak_record(seeds=seeds, replay_timeline=replay,
                                 determinism=determinism,
                                 autoscale=args.autoscale, **kwargs)))
    return 0


def _chaos_main() -> int:
    import argparse

    ap = argparse.ArgumentParser()
    ap.add_argument("--chaos", action="store_true")
    ap.add_argument("--watchdog-s", type=float, default=1.0)
    ap.add_argument("--max-replays", type=int, default=1)
    ap.add_argument("--n-new", type=int, default=16)
    ap.add_argument("--segment", type=int, default=4)
    args = ap.parse_args()
    _enable_compile_cache()
    print(json.dumps(chaos_record(
        watchdog_s=args.watchdog_s, max_replays=args.max_replays,
        n_new=args.n_new, segment=args.segment)))
    return 0


def _pipeline_main() -> int:
    import argparse

    ap = argparse.ArgumentParser()
    ap.add_argument("--pipeline", action="store_true")
    ap.add_argument("--depths", type=str, default="1,2")
    ap.add_argument("--rtts-ms", type=str, default="0,20,66")
    ap.add_argument("--requests", type=int, default=2)
    ap.add_argument("--prompt-len", type=int, default=12)
    ap.add_argument("--n-new", type=int, default=64)
    ap.add_argument("--segment", type=int, default=16)
    ap.add_argument("--slots", type=int, default=4)
    ap.add_argument("--reps", type=int, default=2)
    args = ap.parse_args()
    _enable_compile_cache()
    print(json.dumps(pipeline_record(
        depths=tuple(int(x) for x in args.depths.split(",")),
        rtts_ms=tuple(float(x) for x in args.rtts_ms.split(",")),
        n_requests=args.requests, prompt_len=args.prompt_len,
        n_new=args.n_new, segment=args.segment, slots=args.slots,
        reps=args.reps)))
    return 0


def _paged_main() -> int:
    import argparse

    ap = argparse.ArgumentParser()
    ap.add_argument("--paged", action="store_true")
    ap.add_argument("--requests", type=int, default=4)
    ap.add_argument("--prefix-len", type=int, default=512)
    ap.add_argument("--suffix-len", type=int, default=8)
    ap.add_argument("--n-new", type=int, default=16)
    ap.add_argument("--segment", type=int, default=8)
    ap.add_argument("--slots", type=int, default=4)
    ap.add_argument("--block", type=int, default=64)
    ap.add_argument("--depths", type=str, default="1,2")
    args = ap.parse_args()
    _enable_compile_cache()
    print(json.dumps(paged_record(
        n_requests=args.requests, prefix_len=args.prefix_len,
        suffix_len=args.suffix_len, n_new=args.n_new,
        segment=args.segment, slots=args.slots, block=args.block,
        depths=tuple(int(x) for x in args.depths.split(",")))))
    return 0


def _spec_main() -> int:
    import argparse

    ap = argparse.ArgumentParser()
    ap.add_argument("--spec", action="store_true")
    ap.add_argument("--requests", type=int, default=3)
    ap.add_argument("--n-new", type=int, default=64)
    ap.add_argument("--k", type=int, default=8)
    ap.add_argument("--segment", type=int, default=8)
    ap.add_argument("--slots", type=int, default=4)
    ap.add_argument("--block", type=int, default=32)
    ap.add_argument("--depths", type=str, default="1,2")
    ap.add_argument("--reps", type=int, default=3)
    args = ap.parse_args()
    _enable_compile_cache()
    print(json.dumps(spec_record(
        n_requests=args.requests, n_new=args.n_new, k=args.k,
        segment=args.segment, slots=args.slots, block=args.block,
        depths=tuple(int(x) for x in args.depths.split(",")),
        reps=args.reps)))
    return 0


def _spec_draft_main() -> int:
    import argparse

    # the mesh leg needs >= 2 devices; on the CPU platform that means
    # forcing host devices BEFORE jax initializes (this branch runs
    # before any jax import — bench.py's module top imports none)
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=2").strip()

    ap = argparse.ArgumentParser()
    ap.add_argument("--spec-draft", action="store_true")
    ap.add_argument("--n-new", type=int, default=16)
    ap.add_argument("--n-perf", type=int, default=48)
    ap.add_argument("--n-adv", type=int, default=128)
    ap.add_argument("--k", type=int, default=8)
    ap.add_argument("--segment", type=int, default=8)
    ap.add_argument("--slots", type=int, default=4)
    ap.add_argument("--reps", type=int, default=3)
    args = ap.parse_args()
    _enable_compile_cache()
    print(json.dumps(spec_draft_record(
        n_new=args.n_new, n_perf=args.n_perf, n_adv=args.n_adv,
        k=args.k, segment=args.segment, slots=args.slots,
        reps=args.reps)))
    return 0


def _mesh_main() -> int:
    import argparse

    # the sweep needs >= 2 devices; on the CPU platform that means
    # forcing host devices BEFORE jax initializes (this branch runs
    # before any jax import — bench.py's module top imports none)
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=2").strip()

    ap = argparse.ArgumentParser()
    ap.add_argument("--mesh", action="store_true")
    ap.add_argument("--requests", type=int, default=3)
    ap.add_argument("--n-new", type=int, default=16)
    ap.add_argument("--segment", type=int, default=4)
    ap.add_argument("--slots", type=int, default=4)
    ap.add_argument("--block", type=int, default=32)
    ap.add_argument("--depths", type=str, default="1,2")
    ap.add_argument("--reps", type=int, default=2)
    args = ap.parse_args()
    _enable_compile_cache()
    print(json.dumps(mesh_record(
        n_requests=args.requests, n_new=args.n_new, segment=args.segment,
        slots=args.slots, block=args.block,
        depths=tuple(int(x) for x in args.depths.split(",")),
        reps=args.reps)))
    return 0


def _sp_prefill_main() -> int:
    import argparse

    # the sweep needs >= 2 devices; on the CPU platform that means
    # forcing host devices BEFORE jax initializes (this branch runs
    # before any jax import — bench.py's module top imports none)
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=2").strip()

    ap = argparse.ArgumentParser()
    ap.add_argument("--sp-prefill", action="store_true")
    ap.add_argument("--n-new", type=int, default=12)
    ap.add_argument("--segment", type=int, default=8)
    ap.add_argument("--slots", type=int, default=4)
    ap.add_argument("--block", type=int, default=16)
    ap.add_argument("--walk-ms", type=float, default=150.0)
    ap.add_argument("--max-ratio", type=float, default=0.6)
    ap.add_argument("--ttft-reps", type=int, default=2)
    ap.add_argument("--multipliers", type=str, default="8,16")
    args = ap.parse_args()
    _enable_compile_cache()
    print(json.dumps(sp_prefill_record(
        n_new=args.n_new, segment=args.segment, slots=args.slots,
        block=args.block, walk_ms=args.walk_ms,
        max_ratio=args.max_ratio, ttft_reps=args.ttft_reps,
        multipliers=tuple(int(x)
                          for x in args.multipliers.split(",")))))
    return 0


def _decode_window_main() -> int:
    import argparse

    ap = argparse.ArgumentParser()
    ap.add_argument("--decode-window", action="store_true")
    ap.add_argument("--cache-len", type=int, default=256)
    ap.add_argument("--n-new", type=int, default=24)
    ap.add_argument("--segment", type=int, default=8)
    ap.add_argument("--lens", type=str, default="16,48,200")
    args = ap.parse_args()
    _enable_compile_cache()
    print(json.dumps(decode_window_record(
        lens=tuple(int(x) for x in args.lens.split(",")),
        cache_len=args.cache_len, n_new=args.n_new, segment=args.segment)))
    return 0


def _long_context_main() -> int:
    import argparse

    os.environ.setdefault("JAX_PLATFORMS", "cpu")

    ap = argparse.ArgumentParser()
    ap.add_argument("--long-context", action="store_true")
    ap.add_argument("--multipliers", type=str, default="8,16,32")
    ap.add_argument("--cache-len", type=int, default=128)
    ap.add_argument("--block", type=int, default=16)
    ap.add_argument("--n-new", type=int, default=32)
    ap.add_argument("--segment", type=int, default=8)
    ap.add_argument("--stall-frac-gate", type=float, default=0.10)
    ap.add_argument("--toks-smooth-gate", type=float, default=4.0)
    ap.add_argument("--ttft-slack", type=float, default=3.0)
    args = ap.parse_args()
    _enable_compile_cache()
    print(json.dumps(long_context_record(
        multipliers=tuple(int(x) for x in args.multipliers.split(",")),
        cache_len=args.cache_len, block=args.block, n_new=args.n_new,
        segment=args.segment, stall_frac_gate=args.stall_frac_gate,
        toks_smooth_gate=args.toks_smooth_gate,
        ttft_slack=args.ttft_slack)))
    return 0


def _fleet_main() -> int:
    import argparse

    ap = argparse.ArgumentParser()
    ap.add_argument("--fleet", action="store_true")
    ap.add_argument("--replicas", type=int, default=2)
    ap.add_argument("--requests-per-group", type=int, default=6)
    ap.add_argument("--groups", type=int, default=2)
    ap.add_argument("--prefix-len", type=int, default=64)
    ap.add_argument("--suffix-len", type=int, default=8)
    ap.add_argument("--n-new", type=int, default=8)
    ap.add_argument("--block", type=int, default=16)
    args = ap.parse_args()
    _enable_compile_cache()
    print(json.dumps(fleet_record(
        replicas=args.replicas, requests_per_group=args.requests_per_group,
        groups=args.groups, prefix_len=args.prefix_len,
        suffix_len=args.suffix_len, n_new=args.n_new, block=args.block)))
    return 0


def _shared_prefix_main() -> int:
    import argparse

    ap = argparse.ArgumentParser()
    ap.add_argument("--shared-prefix", action="store_true")
    ap.add_argument("--prefix-len", type=int, default=512)
    ap.add_argument("--suffix-len", type=int, default=16)
    ap.add_argument("--requests", type=int, default=8)
    ap.add_argument("--n-new", type=int, default=16)
    ap.add_argument("--block", type=int, default=64)
    args = ap.parse_args()
    _enable_compile_cache()
    print(json.dumps(shared_prefix_record(
        n_requests=args.requests, prefix_len=args.prefix_len,
        suffix_len=args.suffix_len, n_new=args.n_new, block=args.block)))
    return 0


def _attach_last_device_record(result: dict) -> None:
    """Best-effort: copy the latest published on-chip measurements from
    BASELINE.json into a CPU-fallback bench line."""
    try:
        path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "BASELINE.json")
        with open(path) as f:
            pub = json.load(f).get("published", {})
        note: dict = {}
        c3 = pub.get("config3", {})
        # only records actually measured ON the device qualify — a
        # CPU-fallback publish here would recreate the misattribution
        # this note exists to prevent
        if c3.get("serve_overhead_p50_ms") is not None and \
                c3.get("platform") not in ("cpu", None):
            note["resnet_serve_p50_ms"] = c3["serve_overhead_p50_ms"]
            note["resnet_measured_at"] = c3.get("measured_at")
        c5 = pub.get("config5", {})
        if c5.get("b1_decode_tok_s") is not None and \
                c5.get("platform") not in ("cpu", None):
            note["llama8b_b1_tok_s"] = c5["b1_decode_tok_s"]
            note["llama8b_b8_tok_s"] = c5.get("b8_decode_tok_s")
            note["llama8b_hbm_util"] = c5.get("b1_decode_hbm_util")
            note["llama8b_measured_at"] = c5.get("measured_at")
        spec = c5.get("speculative", {})
        # same device-only gate as the sibling blocks: the mode runs
        # anywhere, so an off-chip publish must not read as a device
        # number (older records lack their own platform field — fall
        # back to the enclosing config5's)
        spec_platform = spec.get("platform", c5.get("platform"))
        if spec.get("spec_tok_s") is not None and \
                spec_platform not in ("cpu", None):
            note["llama8b_spec_tok_s"] = spec["spec_tok_s"]
            note["llama8b_spec_tokens_per_step"] = (
                spec.get("spec_stats", {}).get("tokens_per_step"))
            note["llama8b_spec_measured_at"] = spec.get("measured_at")
        if note:
            result["last_published_device"] = note
    except Exception:  # informational only — never break the bench line
        pass


def _timed(fn) -> float:
    t0 = time.monotonic()
    fn()
    return (time.monotonic() - t0) * 1e3


def _run_stage(stage: str, env: dict, platform: str):
    """Returns (parsed-json | None, error-string | None)."""
    timeout = _stage_timeout(stage, platform)
    here = os.path.abspath(__file__)
    try:
        proc = subprocess.run([sys.executable, here, "--stage", stage],
                              capture_output=True, text=True, env=env,
                              timeout=timeout)
    except subprocess.TimeoutExpired:
        return None, f"{stage}: wedge (timeout after {timeout:.0f}s)"
    if proc.returncode != 0 or not proc.stdout.strip():
        tail = (proc.stderr or "").strip()[-400:]
        return None, f"{stage}: rc={proc.returncode}: {tail}"
    try:
        return json.loads(proc.stdout.strip().splitlines()[-1]), None
    except json.JSONDecodeError:
        return None, f"{stage}: unparseable output {proc.stdout[-200:]!r}"


def main() -> int:
    if "--shared-prefix" in sys.argv:
        # in-process workload mode (no staged orchestration): the
        # shared-prefix serving comparison is CPU-runnable and prints
        # one JSON line like every other bench mode
        return _shared_prefix_main()
    if "--decode-window" in sys.argv:
        # CPU-runnable decode-window sweep: parity + monotone KV-read
        # savings from the length-aware windowed decode path
        return _decode_window_main()
    if "--long-context" in sys.argv:
        # CPU-runnable long-context capacity gate: one fixed page
        # budget serves 8x/16x/32x the compiled window via the sliding
        # logical window + host offload — zero sheds, within-window
        # bitwise parity, smooth TTFT/tok-s degradation, re-online
        # stall fraction bounded with the decode-cursor prefetch live
        return _long_context_main()
    if "--pipeline" in sys.argv:
        # CPU-runnable pipelined-engine sweep: bitwise parity across
        # pipeline depths + depth-2 tok/s beating depth-1 under a
        # synthetic per-fetch transport RTT
        return _pipeline_main()
    if "--spec-draft" in sys.argv:
        # CPU-runnable model-draft speculative tier sweep (forces 2
        # host devices for its mesh leg): bitwise draft-on-vs-off
        # parity (greedy + seeded-sampled, streamed, concurrent, dense
        # + paged + tp=2 mesh, plus an aux DraftProvider leg), >1.5x
        # tok/s over spec-off on a NON-repetitive workload where
        # prompt lookup pays nothing, adaptive per-row k converging
        # upward on easy rows, and adversarial rows demoting
        # model->lookup->off at >= 0.95x spec-off wall-clock
        return _spec_draft_main()
    if "--spec" in sys.argv:
        # CPU-runnable engine-speculation sweep: bitwise spec-on-vs-off
        # parity (greedy + seeded-sampled, cold + prefix-hit, streamed,
        # concurrent, depths 1-2, dense + paged) and the >1.5x tok/s
        # claim on a repetitive-continuation workload, acceptance
        # counters published through batching.spec
        return _spec_main()
    if "--sp-prefill" in sys.argv:
        # CPU-runnable whole-prompt sequence-parallel prefill sweep
        # (forces 2 host devices): bitwise sp-vs-chunked parity —
        # greedy + seeded-sampled, cold + prefix-hit, streamed,
        # concurrent, dense + paged, long-context 8x/16x — plus the
        # cold-TTFT <= 0.6x gate with per-chunk prefill device time
        # modeled through the prefix_walk delay site
        return _sp_prefill_main()
    if "--mesh" in sys.argv:
        # CPU-runnable tensor-parallel sharded-serving sweep (forces 2
        # host devices): bitwise tp=2-vs-tp=1 parity — greedy + sampled,
        # cold + prefix-hit, streamed, concurrent, depths 1-2, dense +
        # paged — plus the per-device KV/param HBM halving gate read
        # from the live batching.mesh gauges; tp=1-vs-tp=2 tok/s printed
        return _mesh_main()
    if "--paged" in sys.argv:
        # CPU-runnable paged-KV sweep: bitwise paged-vs-dense parity
        # (cold/prefix/sampled/streamed, depths 1-2, concurrent), the
        # zero-copy prefix-hit claim (assembly bytes eliminated), and
        # the token-bounded capacity margin under a fixed HBM budget
        return _paged_main()
    if "--disagg-rtt" in sys.argv:
        # synthetic-RTT axis for the pipelined ship: per-chunk wire
        # delay via the kv_ship_chunk fault site — cold TTFT through
        # the chunked relay <= 0.6x the blocking ship's at 66 ms per
        # chunk (transfer hidden under prefill), plus bitwise delivery
        # with zero client errors under permanent mid-stream failure
        return _disagg_rtt_main()
    if "--disagg" in sys.argv:
        # CPU-runnable disaggregated prefill/decode sweep (subprocess
        # replicas): bitwise split-fleet-vs-direct parity (greedy +
        # sampled, dense + paged, real ships observed), decode tok/s
        # under a cold-prefill burst >= 1.2x the mixed fleet at equal
        # replica count, and injected ship failure completing the
        # burst with zero client-visible errors
        return _disagg_main()
    if "--sessions" in sys.argv:
        # CPU-runnable multi-turn session sweep (subprocess replicas):
        # bitwise transcript parity vs direct serving across {greedy,
        # seeded-sampled} x {dense, paged} x {healthy, mid-conversation
        # replica SIGKILL}, zero client-visible errors through failover,
        # turn-2+ TTFT <= 0.15x cold on a healthy home, and pin
        # accounting returning to exactly zero after sessions close
        return _sessions_main()
    if "--soak" in sys.argv:
        # CPU-runnable composed-fault chaos soak (managed subprocess
        # replicas behind the resilient sticky-session router): a
        # seeded nemesis arms overlapping fault-site events, SIGKILLs a
        # worker, and drains a replica while a seeded open-loop mixed
        # workload runs; the history checker asserts zero silent losses
        # (delivered => bitwise vs the direct reference; failed =>
        # explicit priced shed), bounded waiters, and quiesce
        # convergence (invariant sweeps pass, pins/spill -> 0). Exits
        # nonzero on any violation, printing the seed + timeline for
        # one-command replay.
        return _soak_main()
    if "--autoscale" in sys.argv:
        # CPU-runnable elastic control-plane sweep (subprocess
        # replicas): an open-loop prefill spike against a 2-replica
        # mixed fleet — the live controller must promote a prefill
        # replica and recover interactive queue-wait P99 to <= 0.7x
        # the static fleet's, with bitwise delivery, zero silent
        # losses through the role flip, a byte-identical decision
        # replay, and a dry-run leg proving intents never actuate
        return _autoscale_main()
    if "--chaos-fleet" in sys.argv:
        # CPU-runnable fleet-boundary chaos matrix: router-side network
        # faults (drop/latency/mid-body/flap) + a fleet-wide shed burst
        # against a live fleet — zero silent losses, bounded tails, and
        # spill-queue absorption asserted (exits nonzero on violation)
        return _chaos_fleet_main()
    if "--chaos" in sys.argv:
        # CPU-runnable chaos matrix: every fault site x kind injected
        # into a live engine — watchdog bounds, replay parity, ladder
        # and wedge behavior asserted (exits nonzero on any violation)
        return _chaos_main()
    if "--fleet" in sys.argv:
        # CPU-runnable fleet sweep: N replicas behind the affinity
        # router vs one direct — parity + affinity/prefix hit rates
        return _fleet_main()
    if "--stage" in sys.argv:
        stage = sys.argv[sys.argv.index("--stage") + 1]
        return {"devices": _stage_devices, "matmul": _stage_matmul,
                "model": _stage_model, "decode": _stage_decode,
                "decode8b": _stage_decode8b}[stage]()

    here = os.path.dirname(os.path.abspath(__file__))
    base_env = dict(os.environ)
    base_env["PYTHONPATH"] = os.pathsep.join(
        [here] + [p for p in base_env.get("PYTHONPATH", "").split(os.pathsep) if p])

    # FORCE_PLATFORM makes the primary attempt run on that platform while
    # keeping the two-attempt orchestration intact (tests drive the full
    # wedge->fallback path on CPU with it)
    force = os.environ.get("LAMBDIPY_BENCH_FORCE_PLATFORM")
    attempts = [("device", {"LAMBDIPY_PLATFORM": force} if force else {})]
    # an explicit LAMBDIPY_PLATFORM pin is honored: no silent fallback to a
    # different platform than the operator asked to measure
    if force or not os.environ.get("LAMBDIPY_PLATFORM"):
        attempts.append(("cpu", {"LAMBDIPY_PLATFORM": "cpu"}))
    stages_log: dict[str, str] = {}
    for label, extra_env in attempts:
        env = dict(base_env)
        env.update(extra_env)
        env["LAMBDIPY_BENCH_ATTEMPT"] = label
        platform = env.get("LAMBDIPY_PLATFORM") or "device"
        result = None
        if label == "device" and len(attempts) > 1:
            # a previous invocation already diagnosed this transport as
            # wedged: skip straight to the fallback instead of burning
            # the probe timeout again (the verdict file carries a TTL).
            # Only when a fallback attempt exists — an operator's
            # explicit LAMBDIPY_PLATFORM pin (e.g. cpu) runs a single
            # attempt that has nothing to do with the wedged tunnel the
            # verdict diagnosed, and skipping it would fail the run
            # outright
            cached = _read_cached_wedge()
            if cached is not None:
                stages_log["device.devices"] = cached
                continue
        for stage in STAGES:
            data, err = _run_stage(stage, env, platform)
            if err is not None:
                stages_log[f"{label}.{stage}"] = err
                if label == "device" and stage == "devices" \
                        and "wedge" in err:
                    _write_wedge_verdict(err)
                break
            stages_log[f"{label}.{stage}"] = "ok"
            if stage == "model":
                result = data
        if result is not None:
            # best-effort secondary decode metric on the measured platform
            # (skipped on the cpu fallback: slow there and not the story);
            # its failure is recorded but never degrades the headline
            if platform != "cpu":
                for extra_stage in ("decode", "decode8b"):
                    data, err = _run_stage(extra_stage, env, platform)
                    stages_log[f"{label}.{extra_stage}"] = (
                        "ok" if err is None else err)
                    if data is not None:
                        result.update(data)
            if label == "cpu":
                # reaching the cpu attempt means the device attempt
                # failed (e.g. a wedged transport — main() would have
                # returned otherwise): attach the last on-chip record
                # published through the real serve path so this line
                # still tells the true story — CPU numbers here mean
                # the TRANSPORT was down at bench time, not that the
                # stack regressed
                _attach_last_device_record(result)
                # ...and the session's timestamped probe attempts, so
                # the artifact proves reruns were attempted throughout
                # the round, not once at its end (VERDICT r5 #10).
                # Best-effort: a probe killed mid-write leaves a
                # truncated line, and informational context must never
                # break the bench line itself.
                try:
                    probe_log = os.path.join(here, "PROBE_LOG.jsonl")
                    if os.path.isfile(probe_log):
                        with open(probe_log) as f:
                            lines = [ln.strip() for ln in f if ln.strip()]
                        tail = []
                        for ln in lines[-6:]:
                            try:
                                tail.append(json.loads(ln))
                            except json.JSONDecodeError:
                                continue
                        if tail:
                            result["probe_log_tail"] = tail
                except Exception:  # noqa: BLE001
                    pass
            result["stages"] = stages_log
            print(json.dumps(result))
            return 0
    model = os.environ.get("LAMBDIPY_BENCH_MODEL", "resnet50")
    print(json.dumps({
        "metric": f"{model}_b1_fwd_p50",
        "value": -1.0,
        "unit": "ms",
        "vs_baseline": 0.0,
        "error": "all attempts failed",
        "stages": stages_log,
    }))
    return 1


if __name__ == "__main__":
    sys.exit(main())
