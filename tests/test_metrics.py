"""LatencyStats: percentile edge cases + reservoir wraparound (the seed
overwrote with the post-increment count, skewing the ring by one and
making slot 0 immortal). Plus the prefix-cache counter block."""

import threading

from lambdipy_tpu.runtime.metrics import LatencyStats, PrefixCacheStats


def test_empty_reservoir_reports_none():
    stats = LatencyStats()
    report = stats.report()
    assert report["count"] == 0 and report["errors"] == 0
    assert report["p50_ms"] is None
    assert report["p90_ms"] is None
    assert report["p99_ms"] is None
    assert stats.percentile(50) is None


def test_single_sample_every_percentile():
    stats = LatencyStats()
    stats.record(42.0)
    report = stats.report()
    assert report["count"] == 1
    assert report["p50_ms"] == report["p90_ms"] == report["p99_ms"] == 42.0


def test_wraparound_overwrites_oldest_first():
    """After capacity, sample N lands at ring slot N % capacity: the
    FIRST overwrite must hit slot 0 (the oldest sample), not slot 1."""
    stats = LatencyStats(capacity=4)
    for v in (1.0, 2.0, 3.0, 4.0):
        stats.record(v)
    assert stats.samples == [1.0, 2.0, 3.0, 4.0]
    stats.record(5.0)  # 5th sample -> slot 4 % 4 == 0
    assert stats.samples == [5.0, 2.0, 3.0, 4.0]
    stats.record(6.0)
    assert stats.samples == [5.0, 6.0, 3.0, 4.0]
    # a full extra lap replaces everything — no immortal slot
    for v in (7.0, 8.0, 9.0, 10.0):
        stats.record(v)
    assert sorted(stats.samples) == [7.0, 8.0, 9.0, 10.0]
    assert stats.count == 10


def test_percentiles_after_wraparound():
    stats = LatencyStats(capacity=8)
    for v in range(100):
        stats.record(float(v))
    report = stats.report()
    # reservoir holds exactly the last 8 samples: 92..99
    assert report["count"] == 100
    assert report["p50_ms"] >= 92.0
    assert report["p99_ms"] == 99.0


def test_report_under_concurrent_recording():
    """report() snapshots count/errors/samples under the lock; hammer it
    concurrently and require internally consistent output."""
    stats = LatencyStats(capacity=32)
    stop = threading.Event()

    def writer():
        i = 0
        while not stop.is_set():
            stats.record(float(i % 50))
            if i % 7 == 0:
                stats.record_error()
            i += 1

    threads = [threading.Thread(target=writer) for _ in range(4)]
    for t in threads:
        t.start()
    try:
        for _ in range(200):
            report = stats.report()
            if report["count"]:
                assert report["p50_ms"] is not None
                assert 0.0 <= report["p50_ms"] <= 49.0
    finally:
        stop.set()
        for t in threads:
            t.join()
    final = stats.report()
    assert final["count"] > 0 and final["errors"] > 0


def test_prefix_cache_stats_counters():
    """The /metrics counter block the radix prefix store publishes:
    hit/miss/hit_tokens accounting, byte/block bookkeeping through
    insert + evict, and a rate that never divides by zero."""
    st = PrefixCacheStats()
    assert st.report() == {"hits": 0, "misses": 0, "hit_rate": 0.0,
                           "hit_tokens": 0, "evictions": 0, "bytes": 0,
                           "blocks": 0}
    st.record_request(0)        # miss
    st.record_request(64)       # hit, 64 reused tokens
    st.record_request(32)
    st.record_insert(2, 8192)
    st.record_insert(1, 4096)
    st.record_evict(1, 4096)
    rep = st.report()
    assert rep["hits"] == 2 and rep["misses"] == 1
    assert rep["hit_rate"] == round(2 / 3, 4)
    assert rep["hit_tokens"] == 96
    assert rep["blocks"] == 2 and rep["bytes"] == 8192
    assert rep["evictions"] == 1
