"""The model-draft speculative tier: shallow-exit self-drafting,
per-row adaptive k with the provider fallback chain, the DraftProvider
seam (aux twin models), and the knob/policy plumbing that steers it.

Wall-clock discipline mirrors test_spec_engine.py: every non-slow
engine test shares ONE shape (slots=2, segment=4, spec_k=4) over the
session tiny_server, so the model-draft program family ("mspec", kb in
{2, 4}) compiles once for the module. `bench.py --spec-draft` (tier-1
phase 16) carries the expensive matrix — throughput, adaptive-k
convergence, adversarial amortization, mesh + paged parity at scale —
the slow-marked tests here are its in-repo twins."""

import threading
import time
from concurrent.futures import ThreadPoolExecutor
from types import SimpleNamespace

import numpy as np
import pytest

from lambdipy_tpu.runtime.continuous import AuxModelDraft, ContinuousBatcher
from lambdipy_tpu.runtime.metrics import SpecDecodeStats


def _mk(tiny_server, **kw):
    args = dict(slots=2, segment=4, spec_k=4)
    args.update(kw)
    return ContinuousBatcher(tiny_server, **args)


def _fresh_metrics(cb):
    cb.spec_metrics = SpecDecodeStats()
    return cb.spec_metrics


# -- _spec_chain_verify unit edges -----------------------------------------


def _greedy_select():
    import jax.numpy as jnp

    def select(lg, subs):
        lp = jnp.log(jnp.maximum(
            jnp.exp(lg - lg.max(-1, keepdims=True))
            / jnp.exp(lg - lg.max(-1, keepdims=True)).sum(-1,
                                                          keepdims=True),
            1e-38))
        tok = jnp.argmax(lg, axis=-1).astype(jnp.int32)
        return tok, jnp.take_along_axis(lp, tok[:, None], 1)[:, 0]

    return select


def test_chain_verify_accept_and_reject_rows():
    """Full-accept and all-rejected rows in one chunk: count is the
    accepted prefix + the always-correct chain token; a masked draft
    (-1 padding, the provider-failure filler) can never be accepted."""
    import jax
    import jax.numpy as jnp

    from lambdipy_tpu.models.llama import _spec_chain_verify

    b, kb, v = 2, 4, 8
    lg = jnp.zeros((b, kb, v), jnp.float32)
    # the greedy chain at every position of every row is token 5
    lg = lg.at[:, :, 5].set(9.0)
    draft = jnp.asarray([[5, 5, 5],      # matches the chain: full accept
                         [-1, -1, -1]],  # masked filler: nothing accepted
                        jnp.int32)
    lp_in = jnp.asarray([-0.5, -0.25], jnp.float32)
    keys = jax.vmap(lambda s: jax.random.PRNGKey(s))(jnp.arange(2))
    lps, count, tok2, lp2, keys2 = _spec_chain_verify(
        _greedy_select(), lg, draft, lp_in, keys)
    assert count.tolist() == [kb, 1]
    assert tok2.tolist() == [5, 5]
    # column 0 is the pending token's carried logprob, untouched
    np.testing.assert_allclose(np.asarray(lps[:, 0]),
                               np.asarray(lp_in))
    assert lps.shape == (b, kb)


def test_chain_verify_k2_minimum_bucket():
    """kb=2 — the slow-start bucket every model/aux row begins at — is
    a real verify chunk: one draft position, count in {1, 2}."""
    import jax
    import jax.numpy as jnp

    from lambdipy_tpu.models.llama import _spec_chain_verify

    b, kb, v = 2, 2, 8
    lg = jnp.zeros((b, kb, v), jnp.float32).at[:, :, 3].set(4.0)
    draft = jnp.asarray([[3], [4]], jnp.int32)
    keys = jax.vmap(lambda s: jax.random.PRNGKey(s))(jnp.arange(2))
    _, count, tok2, _, _ = _spec_chain_verify(
        _greedy_select(), lg, draft, jnp.zeros((b,), jnp.float32), keys)
    assert count.tolist() == [2, 1]
    assert tok2.tolist() == [3, 3]


def test_chain_verify_key_walk_rolls_back():
    """The rejected tail's PRNG splits roll back: the returned chain
    state is the walk after exactly `count` selections, so a sampled
    row continues bitwise where plain decode would."""
    import jax
    import jax.numpy as jnp

    from lambdipy_tpu.models.llama import (_spec_chain_verify,
                                           _split_rows)

    def sampled(lg, subs):
        tok = jax.vmap(jax.random.categorical)(subs, lg).astype(jnp.int32)
        lp = jax.nn.log_softmax(lg, axis=-1)
        return tok, jnp.take_along_axis(lp, tok[:, None], 1)[:, 0]

    b, kb, v = 1, 4, 16
    key = jax.random.PRNGKey(0)
    lg = jax.random.normal(key, (b, kb, v), jnp.float32) * 3.0
    keys = jax.random.PRNGKey(42)[None, :]
    # walk the chain by hand to learn its tokens, then draft a prefix
    # of them so exactly 2 drafts are accepted (count = 3)
    cur, chain = keys, []
    for i in range(kb):
        cur, subs = _split_rows(cur)
        chain.append(int(sampled(lg[:, i, :], subs)[0][0]))
    wrong = (chain[2] + 1) % v
    draft = jnp.asarray([[chain[0], chain[1], wrong]], jnp.int32)
    _, count, tok2, _, keys2 = _spec_chain_verify(
        sampled, lg, draft, jnp.zeros((b,), jnp.float32), keys)
    assert int(count[0]) == 3
    assert int(tok2[0]) == chain[2]
    expect = keys
    for _ in range(3):
        expect, _ = _split_rows(expect)
    np.testing.assert_array_equal(np.asarray(keys2), np.asarray(expect))


def test_lookup_draft_hit_edges():
    """Empty context drafts zeros (miss); no n-gram match repeats the
    last token (miss); a match extrapolates the earlier continuation,
    padded with the last token when it runs short (still a hit)."""
    from lambdipy_tpu.models.llama import _lookup_draft_hit

    assert _lookup_draft_hit([], 3) == ([0, 0, 0], False)
    d, hit = _lookup_draft_hit([1, 2, 3, 4], 3)
    assert (d, hit) == ([4, 4, 4], False)
    d, hit = _lookup_draft_hit([7, 8, 9, 7, 8], 2)
    assert (d, hit) == ([9, 7], True)
    # the continuation after the match is shorter than k: pad-last
    d, hit = _lookup_draft_hit([5, 6, 5], 4)
    assert (d, hit) == ([6, 5, 5, 5], True)


# -- shallow exit ----------------------------------------------------------


def test_shallow_exit_full_depth_is_identity():
    """exit_layer == cfg.layers routes the exact full forward (same
    params looked up, same ops) — the shallow head is a strict prefix
    of the model, not a parallel approximation."""
    from lambdipy_tpu.models import registry

    adapter = registry.get("llama-tiny").build()
    params = adapter.init_params(seed=0)
    import jax.numpy as jnp

    toks = jnp.asarray([[1, 2, 3, 4, 5]], jnp.int32)
    full, _ = adapter.module.apply(params, toks)
    shallow, cache = adapter.module.apply(
        params, toks, exit_layer=adapter.config.layers)
    np.testing.assert_array_equal(np.asarray(full), np.asarray(shallow))
    assert len(cache) == adapter.config.layers
    # a genuinely shallow exit carries one cache entry per RUN layer
    early, cache1 = adapter.module.apply(params, toks, exit_layer=1)
    assert early.shape == full.shape and len(cache1) == 1


# -- engine parity: the model-draft tier -----------------------------------


def test_model_draft_engine_parity(tiny_server):
    """The tier's bitwise contract: model-drafted rows (greedy and
    seeded-sampled, concurrent) emit exactly their solo outputs —
    drafts change tokens-per-weight-read, never the tokens — and the
    draft block appears on the metrics surface."""
    cb = _mk(tiny_server, draft_mode="model")
    metrics = _fresh_metrics(cb)
    prompts = [[5, 6, 7, 8], [9, 8, 7]]
    kws = [dict(), dict(temperature=0.8, seed=11)]
    solo = [tiny_server.generate(p, max_new_tokens=16, **kw)
            for p, kw in zip(prompts, kws)]

    def run(i):
        time.sleep(0.01 * i)
        return cb.generate(prompts[i], max_new_tokens=16, **kws[i])

    with ThreadPoolExecutor(max_workers=2) as ex:
        outs = list(ex.map(run, range(2)))
    for i, o in enumerate(outs):
        np.testing.assert_array_equal(o, solo[i], err_msg=f"row {i}")
    rep = metrics.report()
    assert rep["draft"]["providers"], rep["draft"]
    # slow-start: every dispatched k is a pow-2 within [2, spec_k]
    assert set(rep["draft"]["k_hist"]) <= {"2", "4"}, rep["draft"]


def test_model_draft_budget_shorter_than_k(tiny_server):
    """A row whose remaining budget is smaller than the draft width
    still lands bitwise: the verify chunk may overshoot, the collector
    truncates to the budget exactly like the plain engine."""
    cb = _mk(tiny_server, draft_mode="model")
    for n in (1, 3):
        ref = tiny_server.generate([5, 6, 7, 8], max_new_tokens=n)
        out = cb.generate([5, 6, 7, 8], max_new_tokens=n)
        np.testing.assert_array_equal(out, ref)


@pytest.mark.slow  # bench.py --spec-draft (tier-1 phase 16) gates
# depth-2 model-draft parity on every CI pass; this is its in-repo twin
def test_model_draft_pipeline_depth2(tiny_server):
    """Depth >= 2 composes with the model tier: the shallow chain runs
    in-program off the device-true carry, so drafts are never stale and
    outputs stay bitwise solo's."""
    cb = _mk(tiny_server, draft_mode="model", pipeline_depth=2)
    ref = tiny_server.generate([5, 6, 7, 8], max_new_tokens=16)
    ref_s = tiny_server.generate([2, 4, 6], max_new_tokens=16,
                                 temperature=0.9, seed=5)
    np.testing.assert_array_equal(
        cb.generate([5, 6, 7, 8], max_new_tokens=16), ref)
    np.testing.assert_array_equal(
        cb.generate([2, 4, 6], max_new_tokens=16, temperature=0.9,
                    seed=5), ref_s)


@pytest.mark.slow  # fresh model + paged mspec program family; bench
# phase 16 runs the paged model-draft matrix on every CI pass
def test_model_draft_paged_parity():
    """The paged twin of the model tier (_mspec_pseg_fn): shallow
    drafts over gathered pages, rejected tails absorbed by the null
    page — cold and sampled rows bitwise solo."""
    from lambdipy_tpu.models import registry
    from lambdipy_tpu.models.llama import init_page_arena, page_kv_bytes
    from lambdipy_tpu.runtime.pagepool import PagePool, page_width

    adapter = registry.get("llama-tiny").build()
    cfg = adapter.config
    server = adapter.make_server(adapter.init_params(seed=0))
    block = 16
    page = page_width(cfg.max_len, block)
    n_pages = 2 * (cfg.max_len // page) + 1
    pool = PagePool(n_pages=n_pages, page=page,
                    page_bytes=page_kv_bytes(cfg, page),
                    make_arena=lambda n=n_pages: init_page_arena(
                        cfg, n, page))
    cb = ContinuousBatcher(server, slots=2, segment=4, page_pool=pool,
                           spec_k=4, draft_mode="model")
    ref = server.generate([5, 6, 7, 8], max_new_tokens=12)
    np.testing.assert_array_equal(
        cb.generate([5, 6, 7, 8], max_new_tokens=12), ref)
    refs = server.generate([9, 8, 7], max_new_tokens=12,
                           temperature=0.9, seed=4)
    np.testing.assert_array_equal(
        cb.generate([9, 8, 7], max_new_tokens=12, temperature=0.9,
                    seed=4), refs)
    with cb._lock:
        while cb._engine_running:
            cb._lock.wait(0.05)
    pool.check_invariants()


# -- per-row adaptive k + the fallback chain -------------------------------


def test_spec_row_init_modes(tiny_server):
    """Admission state by engine mode: lookup keeps the legacy fixed k
    (no adaptivity); model/aux slow-start at the k=2 minimum bucket;
    off (or spec_k=0) admits plain rows."""
    assert _mk(tiny_server)._spec_row_init() == ("lookup", 4)
    assert _mk(tiny_server,
               draft_mode="model")._spec_row_init() == ("model", 2)
    assert _mk(tiny_server,
               draft_mode="off")._spec_row_init() == ("off", 1)
    assert _mk(tiny_server, spec_k=0,
               draft_mode="model")._spec_row_init() == ("off", 1)


def test_spec_adapt_grow_shrink_demote(tiny_server):
    """The per-row controller's whole state machine, driven directly:
    sustained acceptance grows k pow-2 up to spec_k, collapse shrinks
    it back to the minimum bucket, and collapse AT k=2 demotes the row
    down the sticky fallback chain model -> lookup -> off, counted
    under batching.spec.draft.fallbacks."""
    cb = _mk(tiny_server, draft_mode="model")
    metrics = _fresh_metrics(cb)
    entry = {"draft_mode": "model", "k_row": 2, "accept_ewma": None}
    cb._spec_adapt(entry, "model", 2, 2)          # frac 1.0: grow
    assert entry["k_row"] == 4 and entry["accept_ewma"] == 1.0
    cb._spec_adapt(entry, "model", 4, 4)          # capped at spec_k
    assert entry["k_row"] == 4
    for _ in range(3):                            # frac 0: ewma decays
        cb._spec_adapt(entry, "model", 4, 1)      # 0.7, 0.49, 0.343
    assert entry["k_row"] == 2, entry             # shrank, not demoted
    assert entry["draft_mode"] == "model"
    while entry["draft_mode"] == "model":         # collapse at k=2
        cb._spec_adapt(entry, "model", 2, 1)
    assert entry == {"draft_mode": "lookup", "k_row": 2,
                     "accept_ewma": None}
    cb._spec_adapt(entry, "lookup", 2, 1)         # fresh ewma 0.0
    assert entry["draft_mode"] == "off" and entry["k_row"] == 1
    assert metrics.report()["draft"]["fallbacks"] == {
        "model->lookup": 1, "lookup->off": 1}


def test_spec_adapt_stale_step_and_legacy_inert(tiny_server):
    """A step collected AFTER its row was demoted (depth >= 2) feeds
    the EWMA but never re-tunes k for the new provider; legacy lookup
    mode is entirely inert (fixed k, no demotion)."""
    cb = _mk(tiny_server, draft_mode="model")
    entry = {"draft_mode": "lookup", "k_row": 2, "accept_ewma": None}
    cb._spec_adapt(entry, "model", 4, 4)          # stale model step
    assert entry["k_row"] == 2 and entry["accept_ewma"] == 1.0
    legacy = _mk(tiny_server)                     # draft_mode="lookup"
    e2 = {"draft_mode": "lookup", "k_row": 4, "accept_ewma": None}
    legacy._spec_adapt(e2, "lookup", 4, 1)
    assert e2 == {"draft_mode": "lookup", "k_row": 4,
                  "accept_ewma": None}


def test_provider_switch_mid_row(tiny_server):
    """An adversarial row (sampled hot: greedy shallow drafts never
    match the chain) walks the whole fallback chain inside ONE request
    — model -> lookup -> off — while staying bitwise solo, and every
    dispatched k stays at the slow-start minimum bucket."""
    cb = _mk(tiny_server, draft_mode="model")
    metrics = _fresh_metrics(cb)
    kw = dict(temperature=1.5, seed=13)
    ref = tiny_server.generate([3, 1, 4, 1], max_new_tokens=24, **kw)
    out = cb.generate([3, 1, 4, 1], max_new_tokens=24, **kw)
    np.testing.assert_array_equal(out, ref)
    rep = metrics.report()["draft"]
    assert rep["fallbacks"].get("model->lookup", 0) >= 1, rep
    assert rep["fallbacks"].get("lookup->off", 0) >= 1, rep
    assert set(rep["k_hist"]) == {"2"}, rep


# -- the DraftProvider seam (aux twin models) ------------------------------


def test_draft_twin_and_aux_provider():
    """registry.draft_twin shrinks a llama-family adapter into a
    same-vocab TP-replicated draft server; AuxModelDraft adapts it to
    the DraftProvider seam with deterministic proposals."""
    from lambdipy_tpu.models import registry

    adapter = registry.get("llama-tiny").build()
    twin = registry.draft_twin(adapter, layers=1)
    prov = AuxModelDraft(twin)
    a = prov.propose([1, 2, 3], 4)
    assert len(a) == 4
    assert all(0 <= t < adapter.config.vocab_size for t in a)
    assert prov.propose([1, 2, 3], 4) == a


def test_draft_twin_rejects_non_llama():
    from lambdipy_tpu.models import registry
    from lambdipy_tpu.models.registry import ModelError

    with pytest.raises(ModelError):
        registry.draft_twin(SimpleNamespace(config=None), layers=1)


def test_aux_engine_parity(tiny_server):
    """draft_mode="aux" through the engine: a separate 1-layer twin
    proposes, the chain verifies — greedy parity holds and the aux
    provider shows up in the per-provider counters."""
    from lambdipy_tpu.models import registry

    adapter = registry.get("llama-tiny").build()
    prov = AuxModelDraft(registry.draft_twin(adapter, layers=1))
    cb = _mk(tiny_server, draft_mode="aux", draft_provider=prov)
    metrics = _fresh_metrics(cb)
    ref = tiny_server.generate([5, 6, 7, 8], max_new_tokens=12)
    out = cb.generate([5, 6, 7, 8], max_new_tokens=12)
    np.testing.assert_array_equal(out, ref)
    provs = metrics.report()["draft"]["providers"]
    assert "aux" in provs or "lookup" in provs or "off" in provs, provs


def test_misbehaving_provider_degrades_safely(tiny_server):
    """A provider that raises or proposes garbage can only miss: the
    pad is RAW -1 (never accepted), so the row degrades toward plain
    decode while the output stays bitwise solo's."""

    class Hostile:
        def __init__(self):
            self.n = 0

        def propose(self, context, k):
            self.n += 1
            if self.n % 2:
                raise RuntimeError("injected provider failure")
            return [0] * (int(k) // 2)   # short AND wrong

    cb = _mk(tiny_server, draft_mode="aux", draft_provider=Hostile())
    ref = tiny_server.generate([5, 6, 7, 8], max_new_tokens=16)
    out = cb.generate([5, 6, 7, 8], max_new_tokens=16)
    np.testing.assert_array_equal(out, ref)


# -- metrics: the batching.spec.draft block --------------------------------


def test_spec_stats_draft_block():
    s = SpecDecodeStats()
    s.record_step(proposed=3, accepted=3, emitted=4, hit=True,
                  provider="model", k=4)
    s.record_step(proposed=3, accepted=3, emitted=4, hit=True,
                  provider="model", k=4)
    s.record_step(proposed=1, accepted=0, emitted=1, hit=False,
                  provider="lookup", k=2)
    s.record_draft_fallback("model->lookup")
    d = s.report()["draft"]
    assert d["providers"]["model"] == {
        "steps": 2, "proposed": 6, "accepted": 6, "acceptance_ewma": 1.0}
    assert d["providers"]["lookup"]["acceptance_ewma"] == 0.0
    assert d["k_hist"] == {"2": 1, "4": 2}
    assert d["fallbacks"] == {"model->lookup": 1}


# -- knob plumbing: /v1/debug/knobs draft_mode -----------------------------


@pytest.mark.slow  # two bundle loads; the validation itself is a pure
# dict-in/dict-out fn and bench phase 16 drives the live knob at scale
def test_knobs_draft_mode_validation(tmp_path):
    """The admin knob's whole validation surface: auto aliases model,
    model/aux require a spec-on boot, aux additionally a wired
    provider, lookup/off always retune, junk is rejected."""
    from lambdipy_tpu.runtime.loader import load_bundle
    from tests.test_runtime import make_model_bundle

    bundle = make_model_bundle(
        tmp_path / "spec", model="llama-tiny",
        handler="lambdipy_tpu.runtime.handlers:generate_handler",
        extra={"max_new_tokens": "8", "batch_mode": "continuous",
               "batch_max": "2", "batch_segment": "4", "spec_k": "4"})
    report = load_bundle(bundle, warmup=False)
    knobs = report.state.knobs_admin_fn
    out = knobs({"draft_mode": "auto"})
    assert out["ok"] and out["draft_mode"] == "model"
    assert not knobs({"draft_mode": "banana"})["ok"]
    assert "draft_provider" in knobs({"draft_mode": "aux"})["error"]
    assert knobs({"draft_mode": "off"})["ok"]
    assert knobs({"draft_mode": "lookup"})["ok"]
    assert not knobs({"draft_mode": "model", "nonsense": 1})["ok"]

    plain_bundle = make_model_bundle(
        tmp_path / "plain", model="llama-tiny",
        handler="lambdipy_tpu.runtime.handlers:generate_handler",
        extra={"max_new_tokens": "8", "batch_mode": "continuous",
               "batch_max": "2", "batch_segment": "4"})
    plain = load_bundle(plain_bundle, warmup=False)
    pk = plain.state.knobs_admin_fn
    # spec off at boot: the tier can be steered down, never enabled
    assert "off at boot" in pk({"draft_mode": "model"})["error"]
    assert pk({"draft_mode": "lookup"})["ok"]


# -- policy + controller: the demote rule end to end -----------------------


def _view(name, **kw):
    from lambdipy_tpu.fleet.policy import ReplicaView

    args = dict(name=name, spec_k=4, draft_mode="model",
                draft_acceptance=0.05)
    args.update(kw)
    return ReplicaView(**args)


def test_policy_demotes_collapsed_draft_mode():
    """A routable replica whose model provider's acceptance EWMA sits
    below the floor gets draft_mode retuned to lookup; healthy, inert
    (lookup/off), unroutable, and signal-less replicas do not."""
    from lambdipy_tpu.fleet.policy import (SET_KNOB, PolicyConfig,
                                           PolicyState, Snapshot, decide)

    snap = Snapshot(t=100.0, replicas=(
        _view("r-collapsed"),
        _view("r-healthy", draft_acceptance=0.9),
        _view("r-lookup", draft_mode="lookup"),
        _view("r-unroutable", routable=False),
        _view("r-blind", draft_acceptance=None),
    ))
    actions = decide(snap, PolicyState(), PolicyConfig())
    assert [(a.kind, a.target, a.knob, a.value) for a in actions] == [
        (SET_KNOB, "r-collapsed", "draft_mode", "lookup")]


def test_policy_demote_respects_knob_cooldown():
    from lambdipy_tpu.fleet.policy import (PolicyConfig, PolicyState,
                                           Snapshot, decide)

    cfg = PolicyConfig()
    state = PolicyState()
    reps = (_view("r1"),)
    assert decide(Snapshot(t=10.0, replicas=reps), state, cfg)
    # inside the cooldown window the same retune is NOT re-emitted
    assert not decide(Snapshot(t=10.0 + cfg.knob_cooldown_s / 2,
                               replicas=reps), state, cfg)
    assert decide(Snapshot(t=10.0 + cfg.knob_cooldown_s + 1,
                           replicas=reps), state, cfg)


def test_controller_snapshot_extracts_draft_signals():
    """build_snapshot lifts batching.spec.draft off a /metrics scrape
    into the ReplicaView the demote rule reads — and a scrape without
    the draft block degrades to None, not a guess."""
    from lambdipy_tpu.fleet.controller import FleetController
    from lambdipy_tpu.fleet.policy import decide

    reps = {
        "r1": SimpleNamespace(name="r1", role="mixed", routable=True,
                              managed=False, outstanding=0,
                              state="ready"),
        "r2": SimpleNamespace(name="r2", role="mixed", routable=True,
                              managed=False, outstanding=0,
                              state="ready"),
    }
    router = SimpleNamespace(
        pool=SimpleNamespace(_lock=threading.Lock(), replicas=reps),
        ship_window=4)
    ctl = FleetController(router, interval_s=1.0, dry_run=True)
    snap = ctl.build_snapshot({
        "fleet": {},
        "replicas": {
            "r1": {"handler": {"batching": {"spec": {
                "k": 4, "acceptance_rate": 0.5, "draft_mode": "model",
                "draft": {"providers": {
                    "model": {"acceptance_ewma": 0.07}}},
            }}}},
            "r2": {"handler": {"batching": {}}},
        }}, t=50.0)
    v1, v2 = snap.replicas
    assert (v1.draft_mode, v1.draft_acceptance) == ("model", 0.07)
    assert (v2.draft_mode, v2.draft_acceptance) == (None, None)
    # the scraped signal drives the demote end to end
    actions = decide(snap, ctl.state, ctl.config)
    assert [(a.target, a.knob, a.value) for a in actions] == [
        ("r1", "draft_mode", "lookup")]
