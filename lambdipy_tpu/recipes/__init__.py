"""Recipe store: per-package build recipes with TPU device variants.

The reference keeps in-repo recipe definitions per heavy package (supported
versions, build steps, prune rules; SURVEY.md §3.1 component #3). Here a
recipe is a validated TOML file under ``lambdipy_tpu/recipes/builtin/``;
model recipes additionally declare a JAX payload (model + params + handler).
"""

from lambdipy_tpu.recipes.schema import (
    BuildSpec,
    PayloadSpec,
    PruneSpec,
    Recipe,
    RecipeError,
    load_recipe_file,
    load_recipe_dict,
)
from lambdipy_tpu.recipes.store import RecipeStore, builtin_store

__all__ = [
    "BuildSpec",
    "PayloadSpec",
    "PruneSpec",
    "Recipe",
    "RecipeError",
    "RecipeStore",
    "builtin_store",
    "load_recipe_file",
    "load_recipe_dict",
]
