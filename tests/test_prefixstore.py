"""Automatic cross-request prefix KV cache (radix reuse): bitwise
on/off parity — greedy and seeded-sampled, solo, streamed and under
concurrent continuous-batching traffic — plus budget eviction, the
scheduler's suffix pricing, and the bench workload's roofline win."""

from concurrent.futures import ThreadPoolExecutor

import numpy as np
import pytest

from lambdipy_tpu.runtime.prefixstore import PrefixStore


@pytest.fixture(scope="module")
def tiny_server():
    from lambdipy_tpu.models import registry

    adapter = registry.get("llama-tiny").build()
    params = adapter.init_params(seed=0)
    return adapter.make_server(params)


def test_radix_match_extend_and_counters(tiny_server):
    """Cold prompt inserts its whole blocks (miss), a sharing prompt
    hits, a longer one extends the match — counters track each."""
    store = PrefixStore(tiny_server, block=16, budget_mb=8)
    row = list(range(1, 41)) + [7, 8, 9]  # 43 tokens -> 32 cacheable
    assert store.route(row) == 32
    st = store.stats()
    assert (st["misses"], st["hits"], st["blocks"]) == (1, 0, 2)
    # shares both blocks -> hit, no new insertion
    row2 = row[:32] + [5, 5, 5, 5, 5]
    assert store.route(row2) == 32
    st = store.stats()
    assert (st["hits"], st["hit_tokens"], st["blocks"]) == (1, 32, 2)
    # extends one block past the match
    row3 = row[:43] + list(range(50, 60))  # 53 tokens -> 48 cacheable
    assert store.route(row3) == 48
    st = store.stats()
    assert (st["hits"], st["hit_tokens"], st["blocks"]) == (2, 64, 3)
    # sub-block prompts can never cache and are not counted
    assert store.route([1, 2, 3]) == 0
    assert store.stats()["misses"] == 1
    # a prompt the model can never serve must not walk (or pollute the
    # LRU / burn a window of prefill) — it stands down untouched
    before = store.stats()
    assert store.route(list(range(1, 300))) == 0  # > max_len (128)
    assert store.stats() == before
    assert store.match_len(row3) == 48 and store.match_len([9, 9]) == 0


def test_bitwise_parity_greedy_sampled_and_reassembly(tiny_server):
    """Routed output is BITWISE the unrouted output for greedy and
    seeded-sampled decode — including after the assembled full-window
    cache is dropped and must reassemble from the tree's block
    slices."""
    store = PrefixStore(tiny_server, block=16, budget_mb=8)
    row = list(range(3, 45))  # 42 tokens -> 32 cacheable
    for kw in ({}, dict(temperature=0.9, seed=7, top_k=5, top_p=0.95)):
        off = tiny_server.generate(row, max_new_tokens=8, **kw)
        m = store.route(row)
        assert m == 32
        on = tiny_server.generate(row[m:], prefix=row[:m],
                                  max_new_tokens=8, **kw)
        np.testing.assert_array_equal(on, off, err_msg=str(kw))
    # drop the assembled entries: the next route must reassemble the
    # full-window cache from stored blocks, with identical output
    with tiny_server._prefix_lock:
        tiny_server._prefixes.clear()
    off = tiny_server.generate(row, max_new_tokens=8)
    m = store.route(row)
    on = tiny_server.generate(row[m:], prefix=row[:m], max_new_tokens=8)
    np.testing.assert_array_equal(on, off)


def test_streamed_parity_from_routed_prefix(tiny_server):
    """Streaming from a radix-matched prefix concatenates to the fused
    unrouted output."""
    store = PrefixStore(tiny_server, block=16, budget_mb=8)
    row = list(range(2, 40))  # 38 tokens -> 32 cacheable
    off = tiny_server.generate(row, max_new_tokens=11)
    m = store.route(row)
    chunks = list(tiny_server.generate_stream(
        row[m:], prefix=row[:m], max_new_tokens=11, segment=4))
    np.testing.assert_array_equal(np.concatenate(chunks, axis=1), off)


def test_parity_under_concurrent_continuous_traffic(tiny_server):
    """The acceptance bar: routed requests join the continuous engine
    next to unrouted traffic and every row's tokens are bitwise its
    solo output — greedy and seeded-sampled, cold and hot."""
    from lambdipy_tpu.runtime.continuous import ContinuousBatcher

    cb = ContinuousBatcher(tiny_server, slots=4, segment=4)
    store = PrefixStore(tiny_server, block=16, budget_mb=8)
    shared = list(range(1, 34))  # 33 tokens of shared material
    reqs = [
        dict(row=shared + [40, 41], kw={}),
        dict(row=shared + [50, 51, 52], kw=dict(temperature=0.9, seed=7)),
        dict(row=[9, 8, 7], kw={}),  # unrouted neighbor
        dict(row=shared + [60], kw=dict(temperature=1.2, top_k=3, seed=3)),
    ]
    solo = [tiny_server.generate(r["row"], max_new_tokens=8, **r["kw"])
            for r in reqs]
    # seed the tree once so the concurrent burst actually HITS (a fully
    # concurrent cold burst counts as misses — each arrives before any
    # insertion lands; the inflight dedup still collapses the walk)
    store.route(reqs[0]["row"])

    def run(r):
        row = r["row"]
        m = store.route(row)
        if m > 0:
            return cb.generate(row[m:], max_new_tokens=8, prefix=row[:m],
                               **r["kw"])
        return cb.generate(row, max_new_tokens=8, **r["kw"])

    with ThreadPoolExecutor(max_workers=4) as ex:
        futs = [ex.submit(run, r) for r in reqs]
        for i, f in enumerate(futs):
            np.testing.assert_array_equal(f.result(), solo[i],
                                          err_msg=f"request {i} diverged")
    stats = cb.stats()
    assert stats["prefix_joins"] >= 2, stats
    assert store.stats()["hits"] >= 2, store.stats()


def test_budget_evicts_lru_leaf_blocks(tiny_server):
    """Inserts beyond the HBM budget evict least-recently-used leaf
    blocks; bytes stay within budget and the counters say so."""
    store = PrefixStore(tiny_server, block=16, budget_mb=8)
    # measure a block's bytes from a first insert
    store.route(list(range(1, 20)))  # 1 block
    per_block = store.stats()["bytes"]
    small = PrefixStore(tiny_server, block=16,
                        budget_mb=1.5 * per_block / 2**20)
    small.route(list(range(1, 40)))   # 2 blocks -> evicts down to 1
    st = small.stats()
    assert st["evictions"] >= 1, st
    assert st["bytes"] <= small.budget_bytes, st
    # the surviving tree still serves correct (possibly shorter) matches
    row = list(range(1, 40))
    off = tiny_server.generate(row, max_new_tokens=8)
    m = small.route(row)
    if m > 0:
        on = tiny_server.generate(row[m:], prefix=row[:m],
                                  max_new_tokens=8)
        np.testing.assert_array_equal(on, off)


def test_wide_chunk_cold_walk_matches_block_walk(tiny_server, monkeypatch):
    """Cold walks dispatch in wide chunks (here the server's
    prefill_chunk family) with a block-width tail: bitwise the same
    output and the same stored blocks as pure block-width walking."""
    monkeypatch.setattr(tiny_server, "prefill_chunk", 32, raising=False)
    store = PrefixStore(tiny_server, block=16, budget_mb=8)
    assert store.walk_chunk == 32
    row = list(range(1, 92))  # 91 tokens -> target 80: 32-wide x2 + 16
    off = tiny_server.generate(row, max_new_tokens=8)
    m = store.route(row)
    assert m == 80
    on = tiny_server.generate(row[m:], prefix=row[:m], max_new_tokens=8)
    np.testing.assert_array_equal(on, off)
    st = store.stats()
    assert st["blocks"] == 5 and st["assembled_entries"] >= 1
    assert st["assembled_bytes"] > 0


def test_concurrent_cold_requests_collapse_to_one_walk(tiny_server):
    """A thundering herd of first requests for the SAME prefix performs
    one extension walk (inflight dedup), and all of them match."""
    store = PrefixStore(tiny_server, block=16, budget_mb=8)
    row = list(range(5, 60))  # 55 tokens -> 48 cacheable

    with ThreadPoolExecutor(max_workers=4) as ex:
        ms = list(ex.map(lambda _: store.route(list(row)), range(4)))
    assert ms == [48] * 4
    st = store.stats()
    assert st["blocks"] == 3, st  # inserted exactly once


def test_sched_prices_suffix_not_full_prompt(tiny_server):
    """runtime/server.py admission subtracts the prefix probe's matched
    tokens — deadline shedding must price what the device will actually
    prefill."""
    from lambdipy_tpu.runtime.server import _request_token_counts

    store = PrefixStore(tiny_server, block=16, budget_mb=8)
    row = list(range(1, 49))  # 48 tokens -> 32 cacheable (one must stay)
    store.route(row)
    req = {"tokens": row, "max_new_tokens": 8}
    prefill, decode = _request_token_counts(req, prefix_probe=store.match_len)
    assert (prefill, decode) == (len(row) - 32, 8)
    # no probe -> full prompt; explicit prefix -> client's split priced
    assert _request_token_counts(req)[0] == len(row)
    with_prefix = {"tokens": [1, 2], "prefix": row, "max_new_tokens": 4}
    assert _request_token_counts(
        with_prefix, prefix_probe=store.match_len)[0] == len(row) + 2
    # a failing probe is advisory: fall back to the full count
    def boom(_):
        raise RuntimeError("probe down")
    assert _request_token_counts(req, prefix_probe=boom)[0] == len(row)


@pytest.mark.slow  # bundle build + boot (~25 s); the routing logic and
# parity are covered non-slow above — this is the handler wiring proof
def test_handler_routes_automatically(tmp_path):
    """End-to-end through the generate handler: plain token requests
    ride the radix cache by default — the response says so, /metrics
    counters move, and output is bitwise the unrouted multi-row path
    (multi-row requests skip routing, giving an in-bundle reference)."""
    from tests.test_runtime import make_model_bundle
    from lambdipy_tpu.runtime.loader import load_bundle

    bundle = make_model_bundle(
        tmp_path, model="llama-tiny",
        handler="lambdipy_tpu.runtime.handlers:generate_handler",
        extra={"max_new_tokens": "8", "prefix_block": "16",
               "prefix_cache_mb": "8"})
    r = load_bundle(bundle, warmup=True)
    assert r.state.meta["prefix_cache"] is True
    row = list(range(1, 44))
    # multi-row requests skip auto-routing: an unrouted reference
    ref = r.state.invoke({"tokens": [row, row]})
    assert ref["ok"], ref
    first = r.state.invoke({"tokens": row})
    second = r.state.invoke({"tokens": row})
    assert first["ok"] and second["ok"]
    assert first["prefix_cached"] and second["prefix_cached"]
    assert first["tokens"][0] == ref["tokens"][0]
    assert second["tokens"] == first["tokens"]
    assert first["n_prompt"] == len(row)
    pc = r.state.stats()["prefix_cache"]
    assert pc["hits"] >= 1 and pc["misses"] >= 1 and pc["bytes"] > 0
    assert r.state.prefix_probe(row) > 0


def test_roofline_prefill_ratio_at_acceptance_dims():
    """Pure-math check of the acceptance claim: at a repeated 512-token
    prefix (8 requests, 16-token suffixes), suffix-only continuation
    executes >= 4x fewer prefill FLOPs than full-prompt prefill — one
    cold walk plus per-request continuations, the exact accounting
    bench.py --shared-prefix reports."""
    from lambdipy_tpu.models.llama import LLAMA3_8B
    from lambdipy_tpu.utils import roofline

    n, p, s = 8, 512, 16
    off = n * roofline.llama_prefill_cost(
        LLAMA3_8B, batch=1, seq_len=p + s).flops
    on = roofline.llama_prefill_cost(LLAMA3_8B, batch=1, seq_len=p).flops
    on += n * roofline.llama_prefix_continue_cost(
        LLAMA3_8B, suffix_len=s, prefix_len=p).flops
    assert off / on >= 4.0, off / on


@pytest.mark.slow  # two compiled server instances (~20 s); the same
# record is asserted at the acceptance dims by the subprocess test below
def test_bench_shared_prefix_mode_reports_roofline_win():
    """bench.py --shared-prefix: token parity on, nonzero hit rate, and
    the roofline model reports >= 4x fewer prefill FLOPs with the cache
    on for a shared-prefix workload (tiny dims keep this CPU-fast; the
    ratio claim is dims-driven, dominated by prefix/suffix lengths)."""
    import bench

    rec = bench.shared_prefix_record(
        n_requests=8, prefix_len=96, suffix_len=8, n_new=8, block=32,
        extra={"vocab_size": 512, "hidden": 64, "layers": 2, "heads": 4,
               "kv_heads": 2, "mlp": 128, "max_len": 256})
    assert rec["parity"] is True
    assert rec["prefill_flop_ratio"] >= 4.0, rec
    assert rec["prefix_cache"]["hit_rate"] > 0, rec
    assert rec["on_tok_s"] > 0 and rec["off_tok_s"] > 0


@pytest.mark.slow
def test_bench_shared_prefix_default_512(tmp_path):
    """The acceptance workload verbatim: a repeated 512-token prefix
    through `python bench.py --shared-prefix` (subprocess, CPU)."""
    import json
    import os
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = {k: v for k, v in os.environ.items() if k != "XLA_FLAGS"}
    env.update({"JAX_PLATFORMS": "cpu",
                "LAMBDIPY_BENCH_CACHE": str(tmp_path / "cache")})
    proc = subprocess.run(
        [sys.executable, os.path.join(repo, "bench.py"), "--shared-prefix"],
        capture_output=True, text=True, env=env, timeout=900)
    assert proc.returncode == 0, proc.stderr[-2000:]
    rec = json.loads(proc.stdout.strip().splitlines()[-1])
    assert rec["parity"] is True
    assert rec["prefix_len"] == 512
    assert rec["prefill_flop_ratio"] >= 4.0, rec
    assert rec["prefix_cache"]["hit_rate"] > 0, rec
