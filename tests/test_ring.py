"""Ring attention (sequence parallel) vs full attention on the 8-device
virtual mesh (SURVEY.md §5.4 pattern)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from lambdipy_tpu.ops.attention import mha_reference
from lambdipy_tpu.parallel.mesh import make_mesh
from lambdipy_tpu.parallel.ring import ring_attention


def _rand(shape, seed):
    return jnp.asarray(np.random.default_rng(seed).normal(size=shape), jnp.float32)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_matches_full(cpu_devices, causal):
    b, s, h, d = 2, 64, 2, 16  # s shards 8 ways -> 8 tokens per device
    q, k, v = (_rand((b, s, h, d), i) for i in range(3))
    ref = mha_reference(q, k, v, causal=causal)
    mesh = make_mesh({"sp": 8})
    with mesh:
        out = ring_attention(q, k, v, mesh, causal=causal)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(out),
                               rtol=2e-4, atol=2e-4)


def test_ring_attention_gqa(cpu_devices):
    b, s, h, kvh, d = 1, 32, 4, 2, 16
    q = _rand((b, s, h, d), 0)
    k = _rand((b, s, kvh, d), 1)
    v = _rand((b, s, kvh, d), 2)
    ref = mha_reference(q, k, v, causal=True)
    mesh = make_mesh({"sp": 8})
    with mesh:
        out = ring_attention(q, k, v, mesh, causal=True)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(out),
                               rtol=2e-4, atol=2e-4)


def test_ring_attention_composes_with_dp(cpu_devices):
    b, s, h, d = 4, 16, 2, 8
    q, k, v = (_rand((b, s, h, d), i) for i in range(3))
    ref = mha_reference(q, k, v, causal=True)
    mesh = make_mesh({"dp": 2, "sp": 4})
    from jax.sharding import NamedSharding, PartitionSpec as P

    with mesh:
        qs = jax.device_put(q, NamedSharding(mesh, P("dp", "sp")))
        ks = jax.device_put(k, NamedSharding(mesh, P("dp", "sp")))
        vs = jax.device_put(v, NamedSharding(mesh, P("dp", "sp")))
        out = ring_attention(qs, ks, vs, mesh, causal=True)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(out),
                               rtol=2e-4, atol=2e-4)


@pytest.mark.slow  # heavyweight parity; subsystem keeps a fast test
def test_llama_ring_backend_matches_dense(cpu_devices):
    """Llama prefill with attn_backend='ring' on an sp mesh must match the
    dense single-device forward — the long-context serving path."""
    import dataclasses

    from lambdipy_tpu.models.llama import LLAMA_TINY, LlamaModel
    from lambdipy_tpu.parallel.mesh import use_mesh

    cfg_dense = dataclasses.replace(LLAMA_TINY, max_len=64)
    cfg_ring = dataclasses.replace(cfg_dense, attn_backend="ring")
    tokens = jnp.asarray(np.random.default_rng(3).integers(0, 500, (1, 32)),
                         jnp.int32)
    model_d = LlamaModel(cfg_dense)
    params = model_d.init(jax.random.PRNGKey(0), tokens)
    ref, _ = model_d.apply(params, tokens)

    model_r = LlamaModel(cfg_ring)
    mesh = make_mesh({"sp": 8})
    with use_mesh(mesh):
        out, _ = model_r.apply(params, tokens)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(out),
                               rtol=5e-4, atol=5e-4)


def test_llama_flash_backend_matches_dense():
    import dataclasses

    from lambdipy_tpu.models.llama import LLAMA_TINY, LlamaModel

    cfg_dense = dataclasses.replace(LLAMA_TINY, max_len=256)
    cfg_flash = dataclasses.replace(cfg_dense, attn_backend="flash")
    tokens = jnp.asarray(np.random.default_rng(4).integers(0, 500, (1, 128)),
                         jnp.int32)
    model_d = LlamaModel(cfg_dense)
    params = model_d.init(jax.random.PRNGKey(0), tokens)
    ref, _ = model_d.apply(params, tokens)
    out, _ = LlamaModel(cfg_flash).apply(params, tokens)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(out),
                               rtol=5e-4, atol=5e-4)


def test_ring_attention_respects_padding_mask(cpu_devices):
    """A padded batch attends identically under ring and dense backends —
    the kv mask rides the ring with its k/v block (VERDICT r2 weak #8)."""
    import numpy as np
    from lambdipy_tpu.models.llama import _attend
    from lambdipy_tpu.parallel.mesh import make_mesh
    from lambdipy_tpu.parallel.ring import ring_attention

    rng = np.random.default_rng(0)
    b, s, h, d = 2, 16, 4, 8
    q = jnp.asarray(rng.normal(size=(b, s, h, d)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(b, s, h, d)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(b, s, h, d)), jnp.float32)
    lengths = np.array([11, 7])
    mask = jnp.asarray(np.arange(s)[None, :] < lengths[:, None])

    causal = jnp.tril(jnp.ones((s, s), dtype=jnp.bool_))
    dense = _attend(q, k, v, mask[:, None, :] & causal[None, :, :])

    mesh = make_mesh({"sp": 4}, devices=cpu_devices[:4])
    ring = ring_attention(q, k, v, mesh, causal=True, kv_mask=mask)
    # compare only valid query rows (pad-row outputs are garbage by design)
    for row, n in enumerate(lengths):
        np.testing.assert_allclose(np.asarray(dense)[row, :n],
                                   np.asarray(ring)[row, :n],
                                   rtol=1e-5, atol=1e-5)
