"""CLI: ``build / package / deploy / serve / invoke`` + stores admin.

Same command surface shape as the reference's click CLI (SURVEY.md §3.1
#1: ``lambdipy build`` / ``lambdipy package``), extended with the serve-side
commands the TPU rebuild adds (deploy/serve/invoke/stop — SURVEY.md §2
table, publish/deploy row). End state per BASELINE.json:
``lambdipy build jax-resnet50 && lambdipy deploy jax-resnet50``.
"""

from __future__ import annotations

import json
import math
import os
import sys
import tempfile
from pathlib import Path

import click

from lambdipy_tpu.utils.logs import get_logger

log = get_logger("lambdipy.cli")


@click.group()
def main():
    """lambdipy-tpu: TPU-native serverless bundle framework."""
    from lambdipy_tpu.utils.platform import apply_platform_override

    apply_platform_override()


# -- recipe/registry admin --------------------------------------------------


@main.command("recipes")
@click.option("--recipe-dir", type=click.Path(), default=None,
              help="extra recipe dir layered over builtins")
def recipes_cmd(recipe_dir):
    """List available recipes."""
    from lambdipy_tpu.recipes import builtin_store

    store = builtin_store(recipe_dir)
    for name in store.names():
        r = store.get(name)
        kind = "model" if r.is_model else "package"
        click.echo(f"{name:20s} {r.version:10s} {r.device:10s} {kind:8s} {r.description}")


@main.command("show")
@click.argument("recipe_name")
@click.option("--recipe-dir", type=click.Path(), default=None)
def show_cmd(recipe_name, recipe_dir):
    """Show one recipe as JSON."""
    import dataclasses

    from lambdipy_tpu.recipes import builtin_store

    recipe = builtin_store(recipe_dir).get(recipe_name)
    click.echo(json.dumps(dataclasses.asdict(recipe), indent=1, default=str))


@main.command("artifacts")
@click.option("--registry", "registry_dir", type=click.Path(), default=None)
def artifacts_cmd(registry_dir):
    """List artifacts in the local registry."""
    from lambdipy_tpu.resolve.registry import ArtifactRegistry

    for info in ArtifactRegistry(registry_dir).list():
        click.echo(f"{info.artifact_id:45s} {info.size_bytes / 1e6:9.1f}MB  {info.device}")


# -- build / package --------------------------------------------------------


def _pyver() -> str:
    return f"{sys.version_info.major}.{sys.version_info.minor}"


def _registry_lookup(registry, recipe, pyver: str) -> str | None:
    """Artifact id under which this recipe is cached locally, or None.

    Checks the locally computed id, then the ``device=any`` id for the
    same recipe/version/python (a prebuilt asset published for ``any``
    satisfies a device-pinned recipe, but nothing looser does — a
    different python tag or concrete device must not be reused)."""
    import dataclasses

    exact = recipe.artifact_id(pyver)
    any_id = dataclasses.replace(recipe, device="any").artifact_id(pyver)
    for candidate in (exact, any_id):
        if registry.has(candidate):
            return candidate
    return None


def _run_build(recipe, registry, *, out=None, no_smoke=False, no_payload=False,
               warm=True):
    """Build one recipe into a bundle and publish it to the local registry.
    Shared by ``build`` (user path) and ``publish`` (maintainer path)."""
    from lambdipy_tpu.buildengine import build_recipe
    from lambdipy_tpu.bundle import assemble_bundle

    artifact_id = recipe.artifact_id(_pyver())
    workdir = Path(tempfile.mkdtemp(prefix=f"lambdipy-build-{recipe.name}-"))
    result = build_recipe(recipe, workdir, run_smoke=not no_smoke)
    bundle_dir = Path(out) if out else workdir / "bundle"
    with_payload = not no_payload and recipe.is_model
    manifest = assemble_bundle(result, bundle_dir, with_payload=with_payload)
    if warm and with_payload:
        import os
        import subprocess

        env = dict(os.environ)
        repo_root = str(Path(__file__).resolve().parents[1])
        env["PYTHONPATH"] = os.pathsep.join(
            [repo_root] + [p for p in env.get("PYTHONPATH", "").split(os.pathsep) if p])
        # warm on the device the recipe targets: cpu/any recipes must not
        # touch (or wait on) the TPU; tpu recipes use the shell's platform
        if "LAMBDIPY_PLATFORM" not in env and not recipe.device.startswith("tpu"):
            env["LAMBDIPY_PLATFORM"] = "cpu"
        # the TPU tunnel on this image can wedge indefinitely (observed;
        # bench.py carries the same guard) — bound the warm step and treat
        # a timeout like any other warm failure: the bundle still serves,
        # it just pays its first compile at boot
        warm_timeout = float(os.environ.get("LAMBDIPY_WARM_TIMEOUT", "600"))
        try:
            proc = subprocess.run(
                [sys.executable, "-m", "lambdipy_tpu.runtime.warm", str(bundle_dir)],
                capture_output=True, text=True, env=env, timeout=warm_timeout)
        except subprocess.TimeoutExpired:
            click.echo(f"warning: warm timed out after {warm_timeout:.0f}s "
                       f"(device wedged?); bundle still usable", err=True)
            proc = None
        # the warm outcome is part of the bundle's record, not just a
        # build-log line: a failed warm means the bundle pays its first
        # compile at boot, and downstream (deploy, healthz) must see that
        if proc is not None and proc.returncode == 0:
            lines = proc.stdout.strip().splitlines()
            last = lines[-1] if lines else ""
            click.echo(f"warmed: {last}")
            warm_record = {"ok": True}
            try:
                parsed = json.loads(last)
                if isinstance(parsed, dict):
                    warm_record.update(parsed)
            except ValueError:
                pass
        elif proc is not None:
            click.echo(f"warning: warm failed (bundle still usable): "
                       f"{proc.stderr.strip()[-300:]}", err=True)
            warm_record = {"ok": False, "error": proc.stderr.strip()[-300:]}
        else:
            warm_record = {"ok": False,
                           "error": f"timeout after {warm_timeout:.0f}s"}
        from lambdipy_tpu.bundle.format import update_manifest

        manifest = update_manifest(bundle_dir, warm=warm_record)
    if out is None:
        registry.publish(artifact_id, bundle_dir, recipe=recipe.name,
                         version=recipe.version, device=recipe.device,
                         manifest=manifest)
        click.echo(f"built + published {artifact_id}")
    else:
        click.echo(f"built {artifact_id} -> {bundle_dir}")
    p = result.prune
    click.echo(f"size {p.bytes_after / 1e6:.1f}MB (saved {p.bytes_saved / 1e6:.1f}MB); "
               f"skipped optional: {result.skipped_optional or 'none'}")
    return artifact_id


@main.command("build")
@click.argument("recipe_name")
@click.option("--out", type=click.Path(), default=None,
              help="bundle output dir (default: temp + registry publish)")
@click.option("--registry", "registry_dir", type=click.Path(), default=None)
@click.option("--recipe-dir", type=click.Path(), default=None)
@click.option("--release-store", "release_store", type=click.Path(), default=None,
              help="prebuilt-release store to consult before building "
                   "(default: $LAMBDIPY_RELEASE_STORE)")
@click.option("--no-prebuilt", is_flag=True,
              help="skip the prebuilt-release lookup and always build locally")
@click.option("--no-smoke", is_flag=True, help="skip the hermetic import smoke")
@click.option("--no-payload", is_flag=True, help="skip params/handler materialization")
@click.option("--force", is_flag=True, help="rebuild even if the artifact is cached")
@click.option("--warm/--no-warm", default=True,
              help="pre-populate the bundle's XLA compile cache (model recipes)")
def build_cmd(recipe_name, out, registry_dir, recipe_dir, release_store,
              no_prebuilt, no_smoke, no_payload, force, warm):
    """Build a recipe into a bundle: local-registry cache hit, then prebuilt
    release fetch, then local build — the reference's hot path (SURVEY.md
    §4 A: release-index hit downloads, miss falls back to the build path)."""
    from lambdipy_tpu.recipes import builtin_store
    from lambdipy_tpu.resolve.registry import ArtifactRegistry
    from lambdipy_tpu.resolve.releases import ReleaseFetcher, default_store

    from lambdipy_tpu.resolve.releases import ReleaseError

    store = builtin_store(recipe_dir)
    recipe = store.get(recipe_name)
    registry = ArtifactRegistry(registry_dir)

    if not force and out is None:
        cached = _registry_lookup(registry, recipe, _pyver())
        if cached is not None:
            click.echo(f"cache hit: {cached} (use --force to rebuild)")
            return

    if not force and out is None and not no_prebuilt:
        releases = default_store(release_store)
        if releases is not None:
            asset = releases.find_asset(recipe=recipe.name, python=_pyver(),
                                        device=recipe.device,
                                        version=recipe.version)
            if asset is not None:
                try:
                    ReleaseFetcher(releases).fetch_into_registry(asset, registry)
                except ReleaseError as e:
                    click.echo(f"warning: prebuilt fetch failed ({e}); "
                               "building locally", err=True)
                else:
                    click.echo(f"fetched prebuilt {asset.name} "
                               f"(release {asset.tag}) -> {asset.artifact_id}")
                    return

    _run_build(recipe, registry, out=out, no_smoke=no_smoke,
               no_payload=no_payload, warm=warm)


# -- prebuilt releases (maintainer publish / user fetch) ---------------------


def _require_store(release_store):
    from lambdipy_tpu.resolve.releases import STORE_ENV, default_store

    store = default_store(release_store)
    if store is None:
        raise click.ClickException(
            f"no release store: pass --release-store or set {STORE_ENV}")
    return store


@main.command("publish")
@click.argument("recipe_names", nargs=-1)
@click.option("--all", "publish_all", is_flag=True,
              help="publish every builtin recipe")
@click.option("--release-store", "release_store", type=click.Path(), default=None)
@click.option("--tag", default=None,
              help="release tag (default: lambdipy-tpu version)")
@click.option("--registry", "registry_dir", type=click.Path(), default=None)
@click.option("--recipe-dir", type=click.Path(), default=None)
@click.option("--rebuild", is_flag=True, help="rebuild even if cached locally")
@click.option("--warm/--no-warm", default=True)
def publish_cmd(recipe_names, publish_all, release_store, tag, registry_dir,
                recipe_dir, rebuild, warm):
    """Maintainer path: build recipes and upload the bundles as prebuilt
    release assets (SURVEY.md §4 C: build each recipe x python version,
    create/append release, upload asset). Users then ``lambdipy fetch`` /
    ``lambdipy build`` without compiling anything."""
    import tempfile as _tempfile

    from lambdipy_tpu import __version__
    from lambdipy_tpu.recipes import builtin_store
    from lambdipy_tpu.resolve.registry import ArtifactRegistry
    from lambdipy_tpu.resolve.releases import ReleaseError, pack_bundle

    if not recipe_names and not publish_all:
        raise click.ClickException("pass recipe names or --all")
    store = builtin_store(recipe_dir)
    names = list(store.names()) if publish_all else list(recipe_names)
    releases = _require_store(release_store)
    registry = ArtifactRegistry(registry_dir)
    tag = tag or f"v{__version__}"
    failed: list[str] = []
    for name in names:
        recipe = store.get(name)
        if _pyver() not in recipe.python:
            click.echo(f"skip {name}: recipe pins python {recipe.python}")
            continue
        artifact_id = recipe.artifact_id(_pyver())
        if rebuild or not registry.has(artifact_id):
            try:
                _run_build(recipe, registry, warm=warm)
            except Exception as e:
                # one unbuildable recipe (e.g. numpy-src without
                # meson-python) must not abort the whole publish sweep
                click.echo(f"FAILED {name}: {e}", err=True)
                failed.append(name)
                continue
        bundle = registry.fetch(artifact_id)
        with _tempfile.TemporaryDirectory(prefix="lambdipy-publish-") as td:
            archive = pack_bundle(bundle, Path(td) / f"{artifact_id}.tar.gz")
            try:
                asset = releases.upload_asset(
                    tag, archive, artifact_id=artifact_id, recipe=recipe.name,
                    version=recipe.version, python=_pyver(), device=recipe.device)
            except ReleaseError as e:
                raise click.ClickException(str(e)) from e
        click.echo(f"published {asset.name} ({asset.size / 1e6:.1f}MB) "
                   f"-> release {tag}")
    if failed:
        raise click.ClickException(
            f"{len(failed)} recipe(s) failed to build: {', '.join(failed)}")


@main.command("fetch")
@click.argument("recipe_name")
@click.option("--release-store", "release_store", type=click.Path(), default=None)
@click.option("--registry", "registry_dir", type=click.Path(), default=None)
@click.option("--recipe-dir", type=click.Path(), default=None)
def fetch_cmd(recipe_name, release_store, registry_dir, recipe_dir):
    """User path: download a prebuilt bundle from the release store into the
    local registry (hash-verified, cached) — the reference's 'download
    matching release asset' branch without any local build."""
    from lambdipy_tpu.recipes import builtin_store
    from lambdipy_tpu.resolve.registry import ArtifactRegistry
    from lambdipy_tpu.resolve.releases import ReleaseError, ReleaseFetcher

    releases = _require_store(release_store)
    store = builtin_store(recipe_dir)
    device = version = None
    if recipe_name in store:
        recipe = store.get(recipe_name)
        device, version = recipe.device, recipe.version
    asset = releases.find_asset(recipe=recipe_name, python=_pyver(),
                                device=device, version=version)
    if asset is None:
        raise click.ClickException(
            f"no prebuilt asset for {recipe_name!r} (python {_pyver()}) in "
            f"{releases.root}")
    try:
        ReleaseFetcher(releases).fetch_into_registry(
            asset, ArtifactRegistry(registry_dir))
    except ReleaseError as e:
        raise click.ClickException(str(e)) from e
    click.echo(f"fetched {asset.name} (release {asset.tag}) -> {asset.artifact_id}")


@main.command("releases")
@click.option("--release-store", "release_store", type=click.Path(), default=None)
def releases_cmd(release_store):
    """List prebuilt assets in the release store."""
    releases = _require_store(release_store)
    for asset in releases.list_assets():
        click.echo(f"{asset.tag:12s} {asset.name:55s} {asset.size / 1e6:8.1f}MB "
                   f"py{asset.python} {asset.device}")


@main.command("package")
@click.argument("requirements", type=click.Path(exists=True))
@click.option("--out", type=click.Path(), required=True, help="output build/ tree")
@click.option("--recipe-dir", type=click.Path(), default=None)
def package_cmd(requirements, out, recipe_dir):
    """Assemble a deployable tree from a project requirements file: recipe-
    covered deps built via their recipes, plain deps vendored directly
    (SURVEY.md §4 B)."""
    from lambdipy_tpu.buildengine import build_recipe
    from lambdipy_tpu.buildengine.vendor import dependency_closure, vendor_distribution
    from lambdipy_tpu.recipes import builtin_store
    from lambdipy_tpu.resolve import resolve_project

    store = builtin_store(recipe_dir)
    res = resolve_project(Path(requirements), store)
    out_dir = Path(out)
    site = out_dir / "site"
    site.mkdir(parents=True, exist_ok=True)
    for req, recipe_name in res.recipe_covered:
        recipe = store.get(recipe_name)
        workdir = Path(tempfile.mkdtemp(prefix=f"lambdipy-pkg-{recipe.name}-"))
        result = build_recipe(recipe, workdir)
        from lambdipy_tpu.utils.fsutil import copy_tree

        copy_tree(result.site_dir, site)
        click.echo(f"recipe {recipe_name}: {req.pin}")
    vendored = set()
    for req in res.plain:
        for dep in dependency_closure([req.raw]):
            if dep not in vendored and not (site / dep.replace("-", "_")).exists():
                vendor_distribution(dep, site)
                vendored.add(dep)
        click.echo(f"plain dep: {req.pin}")
    click.echo(f"packaged -> {out_dir} (add your handler.py and deploy)")


# -- deploy / serve / invoke ------------------------------------------------


def _resolve_bundle(name_or_dir: str, registry_dir) -> Path:
    from lambdipy_tpu.recipes import builtin_store
    from lambdipy_tpu.resolve.registry import ArtifactRegistry

    path = Path(name_or_dir)
    if path.is_dir() and (path / "manifest.json").exists():
        return path
    registry = ArtifactRegistry(registry_dir)
    store = builtin_store()
    if name_or_dir in store:
        artifact_id = _registry_lookup(registry, store.get(name_or_dir), _pyver())
        if artifact_id is not None:
            return registry.fetch(artifact_id)
        raise click.ClickException(
            f"recipe {name_or_dir!r} has no built artifact; run: lambdipy build {name_or_dir}")
    if registry.has(name_or_dir):
        return registry.fetch(name_or_dir)
    # custom recipes (built with --recipe-dir) aren't in the builtin store;
    # resolve them by the recipe name recorded at publish time
    by_recipe = [a for a in registry.list() if a.recipe == name_or_dir]
    if by_recipe:
        latest = max(by_recipe, key=lambda a: a.created)
        return registry.fetch(latest.artifact_id)
    raise click.ClickException(f"{name_or_dir!r} is neither a bundle dir, recipe, nor artifact id")


@main.command("deploy")
@click.argument("bundle")
@click.option("--name", default=None, help="deployment name (default: recipe/artifact)")
@click.option("--port", type=int, default=0)
@click.option("--registry", "registry_dir", type=click.Path(), default=None)
@click.option("--timeout", type=float, default=300.0)
@click.option("--watchdog/--no-watchdog", default=True,
              help="run under the restart supervisor (crash -> respawn)")
def deploy_cmd(bundle, name, port, registry_dir, timeout, watchdog):
    """Deploy a built bundle to the local TPU runtime."""
    from lambdipy_tpu.runtime.deploy import LocalRuntime

    bundle_dir = _resolve_bundle(bundle, registry_dir)
    dep_name = name or bundle.split("/")[-1]
    dep = LocalRuntime().deploy(dep_name, bundle_dir, port=port,
                                ready_timeout=timeout, watchdog=watchdog)
    click.echo(json.dumps({"name": dep.name, "url": dep.url,
                           "cold_start": dep.cold_start}))


@main.command("serve")
@click.argument("bundle")
@click.option("--port", type=int, default=8080)
@click.option("--registry", "registry_dir", type=click.Path(), default=None)
@click.option("--sched-policy", default=None,
              type=click.Choice(["fifo", "priority", "fair"]),
              help="dequeue policy between request classes "
                   "(default: bundle sched_policy, else fair)")
@click.option("--sched-concurrency", type=int, default=None,
              help="invokes running at once (default 8)")
@click.option("--sched-queue-cap", type=int, default=None,
              help="bounded queue depth; beyond it requests shed 503 "
                   "(default 64)")
@click.option("--sched-rate", type=float, default=None,
              help="per-tenant admission rate, requests/s (keyed by "
                   "x-api-key/x-tenant; 0 = unlimited)")
@click.option("--sched-burst", type=float, default=None,
              help="per-tenant token-bucket burst (default 2x rate)")
@click.option("--prefix-cache-mb", type=float, default=None,
              help="HBM budget (MB) for the automatic cross-request "
                   "prefix KV cache; 0 disables, explicit value also "
                   "opts kv_quant bundles in (default: bundle "
                   "prefix_cache_mb, else 512)")
@click.option("--prefix-block", type=int, default=None,
              help="token-block granularity of prefix reuse (rounded "
                   "to a pow-2 dividing the context window; default 32)")
@click.option("--session-pin-budget", type=float, default=None,
              help="MB of prefix-cache KV open multi-turn sessions may "
                   "PIN out of eviction's reach (x-session-id header / "
                   "session_id body field); beyond it new sessions shed "
                   "503 reason session_pins with Retry-After from the "
                   "lease-expiry horizon (default: half the prefix "
                   "cache budget; clamped to the cache budget)")
@click.option("--session-ttl", type=float, default=None,
              help="absolute session pin lease in seconds — a pinned "
                   "conversation lapses this long after it OPENED even "
                   "if turns keep renewing the idle lease (default "
                   "3600; idle lease defaults to 600, tunable per "
                   "bundle via session_idle_s)")
@click.option("--pipeline-depth", type=int, default=None,
              help="decode segments kept in flight on the device before "
                   "the host fetches the oldest (continuous engine): 1 "
                   "= synchronous dispatch-fetch-book loop, >= 2 "
                   "overlaps device compute with the fetch RTT + host "
                   "bookkeeping (default: bundle pipeline_depth, "
                   "else 2)")
@click.option("--engine-watchdog", type=float, default=None,
              help="seconds after which a hung device-side engine wait "
                   "(dispatch / segment fetch / group prefill) marks "
                   "the engine wedged, aborts its waiters and flips "
                   "/healthz to wedged (continuous engine; 0 disables "
                   "— size it ABOVE the transport's worst-case compile "
                   "wall; default: bundle engine_watchdog_s, else off)")
@click.option("--kv-paged/--no-kv-paged", default=None,
              help="paged KV memory for the continuous engine: one "
                   "refcounted page arena instead of a full decode "
                   "window per slot — admission charges actual tokens "
                   "(more concurrent rows for mixed-length traffic) and "
                   "prefix-cache hits share pages zero-copy. Outputs "
                   "stay bitwise the dense path's. (default: bundle "
                   "kv_paged, else off)")
@click.option("--kv-pages", type=int, default=None,
              help="page count of the paged KV arena (page width = the "
                   "prefix block); default sizes it to the same HBM the "
                   "dense engine would allocate: batch_max x window "
                   "pages + the reserved null page")
@click.option("--max-logical-ctx", type=int, default=None,
              help="long-context tier: serve prompts up to this many "
                   "LOGICAL tokens over the compiled window by sliding "
                   "a windowed block table — evicted KV pages spill to "
                   "a host offload arena and re-online on demand, so a "
                   "128k-token session runs over a 4k compiled window. "
                   "Needs --kv-paged; 0 disables (default: bundle "
                   "max_logical_ctx, else off). Gauges ride /metrics "
                   "under batching.page_pool.kv_offload")
@click.option("--kv-offload/--no-kv-offload", default=None,
              help="host offload tier for the prefix store's paged KV: "
                   "cache-pressure sweeps SPILL cold pages to host RAM "
                   "(kvwire frames) instead of dropping them, and a "
                   "later hit re-onlines the pages in one batched frame "
                   "decode instead of re-prefilling. Failed re-onlines "
                   "degrade to a counted prefill recompute — never a "
                   "wrong token (default: bundle kv_offload, else off)")
@click.option("--kv-offload-mb", type=float, default=None,
              help="host RAM budget of the KV offload arena in MiB "
                   "(default 256); a spill past it falls back to "
                   "dropping the page, counted as a spill refusal")
@click.option("--long-prefill/--no-long-prefill", default=None,
              help="opt the long-context tier's prefill into the "
                   "ring-attention path (parallel/ring.py) when the "
                   "mesh has an sp axis; without one the knob stands "
                   "down counted, never silently")
@click.option("--prefill-mode", type=click.Choice(["chunked", "sp"]),
              default=None,
              help="cold-prefill schedule: 'chunked' (default) runs the "
                   "serial chunk chain; 'sp' runs the whole prompt as "
                   "sequence-parallel rounds over the mesh's sp axis — "
                   "ONE sharded program per round, ~1/sp the TTFT "
                   "critical path. Without an sp mesh axis the knob "
                   "stands down counted, never silently. Live-retunable "
                   "via /v1/debug/knobs; counters ride /metrics under "
                   "batching.prefill")
@click.option("--spec-k", type=int, default=None,
              help="speculative decoding inside the continuous engine: "
                   "each segment drafts up to K-1 tokens per row by "
                   "prompt lookup and verifies them in ONE multi-token "
                   "dispatch, emitting 1..K tokens per weight read. "
                   "Outputs stay bitwise the plain engine's (greedy AND "
                   "seeded-sampled); acceptance counters ride "
                   "/metrics under batching.spec. 0/1 disables "
                   "(default: bundle spec_k, else off)")
@click.option("--draft-mode", type=click.Choice(
                  ["lookup", "model", "aux", "off"]), default=None,
              help="draft provider for --spec-k rows: 'lookup' = prompt "
                   "n-gram drafting (default), 'model' = self-drafting "
                   "shallow-exit head with per-row adaptive k and "
                   "model->lookup->off fallback (the non-repetitive-"
                   "workload tier), 'off' = verify path armed but no "
                   "drafting. Per-provider acceptance + k histogram "
                   "ride /metrics under batching.spec.draft")
@click.option("--draft-exit", type=int, default=None,
              help="layers the shallow-exit draft head runs before its "
                   "tied lm_head (draft cost ~ exit/layers of a full "
                   "forward per proposed token; default 1, clamped to "
                   "the model depth)")
@click.option("--mesh", "mesh_spec", type=str, default=None,
              help="tensor-parallel sharded serving over a device mesh, "
                   "e.g. 'tp=2' (Megatron layout: attention heads + MLP "
                   "hidden sharded over tp, KV cache over kv_heads, "
                   "per-device HBM ~1/tp). Accepts 'tp=2', bare '2', "
                   "'2x2' (dp x tp), or 'off'. Outputs stay bitwise the "
                   "single-device path's; layout + per-device bytes ride "
                   "/metrics under batching.mesh. CPU testing: "
                   "XLA_FLAGS=--xla_force_host_platform_device_count=2 "
                   "(default: bundle mesh extra, else single-device)")
def serve_cmd(bundle, port, registry_dir, sched_policy, sched_concurrency,
              sched_queue_cap, sched_rate, sched_burst, prefix_cache_mb,
              prefix_block, session_pin_budget, session_ttl,
              pipeline_depth, engine_watchdog, kv_paged,
              kv_pages, max_logical_ctx, kv_offload, kv_offload_mb,
              long_prefill, prefill_mode, spec_k, draft_mode, draft_exit,
              mesh_spec):
    """Serve a bundle in the foreground."""
    from lambdipy_tpu.runtime.server import BundleServer

    # the generate handler builds its prefix store INSIDE load_bundle,
    # before this process's server object exists — the CLI choice
    # reaches it through the environment, like LAMBDIPY_SCHED_POLICY
    if prefix_cache_mb is not None:
        os.environ["LAMBDIPY_PREFIX_CACHE_MB"] = str(prefix_cache_mb)
    if prefix_block is not None:
        os.environ["LAMBDIPY_PREFIX_BLOCK"] = str(prefix_block)
    if session_pin_budget is not None:
        os.environ["LAMBDIPY_SESSION_PIN_BUDGET_MB"] = \
            str(session_pin_budget)
    if session_ttl is not None:
        os.environ["LAMBDIPY_SESSION_TTL_S"] = str(session_ttl)
    if pipeline_depth is not None:
        os.environ["LAMBDIPY_PIPELINE_DEPTH"] = str(pipeline_depth)
    if engine_watchdog is not None:
        os.environ["LAMBDIPY_ENGINE_WATCHDOG_S"] = str(engine_watchdog)
    if kv_paged is not None:
        os.environ["LAMBDIPY_KV_PAGED"] = "1" if kv_paged else "0"
    if kv_pages is not None:
        os.environ["LAMBDIPY_KV_PAGES"] = str(kv_pages)
    if max_logical_ctx is not None:
        os.environ["LAMBDIPY_MAX_LOGICAL_CTX"] = str(max_logical_ctx)
    if kv_offload is not None:
        os.environ["LAMBDIPY_KV_OFFLOAD"] = "1" if kv_offload else "0"
    if kv_offload_mb is not None:
        os.environ["LAMBDIPY_KV_OFFLOAD_MB"] = str(kv_offload_mb)
    if long_prefill is not None:
        os.environ["LAMBDIPY_LONG_PREFILL"] = "1" if long_prefill else "0"
    if prefill_mode is not None:
        os.environ["LAMBDIPY_PREFILL_MODE"] = prefill_mode
    if spec_k is not None:
        os.environ["LAMBDIPY_SPEC_K"] = str(spec_k)
    if draft_mode is not None:
        os.environ["LAMBDIPY_DRAFT_MODE"] = draft_mode
    if draft_exit is not None:
        os.environ["LAMBDIPY_DRAFT_EXIT"] = str(draft_exit)
    if mesh_spec is not None:
        # validate at the CLI so a typo'd mesh fails HERE with a clear
        # message instead of inside the bundle boot
        from lambdipy_tpu.parallel.mesh import parse_mesh_spec

        parse_mesh_spec(mesh_spec)
        os.environ["LAMBDIPY_MESH"] = mesh_spec
    # BundleServer resolves the effective policy (bundle extra <
    # LAMBDIPY_SCHED_POLICY env < these flags) and bridges it to the
    # handler's batch formation itself — no env plumbing needed here
    server = BundleServer(
        _resolve_bundle(bundle, registry_dir), port=port,
        sched={"policy": sched_policy,
               "max_concurrency": sched_concurrency,
               "queue_cap": sched_queue_cap,
               "rate": sched_rate, "burst": sched_burst})
    click.echo(json.dumps({"ready": True, "port": server.port,
                           "sched_policy": server.sched.policy.name,
                           "cold_start": server.boot.stages}))
    server.serve_forever()


@main.command("fleet")
@click.argument("bundle")
@click.option("--replicas", "-n", type=int, default=2, show_default=True,
              help="supervised bundle-server replicas to run (decode-"
                   "class when --prefill-replicas > 0, mixed otherwise)")
@click.option("--prefill-replicas", type=int, default=0, show_default=True,
              help="additional PREFILL-class replicas (deployed as "
                   "NAME-p0..M-1): the router splits cold requests — "
                   "prefill runs on a prefill replica (/v1/kv/export), "
                   "the KV blocks ship to the affinity-chosen decode "
                   "replica, and decode packs its far deeper batch "
                   "isolated from prefill bursts; 0 = no phase split")
@click.option("--port", type=int, default=8080, show_default=True,
              help="router port (replicas pick their own free ports)")
@click.option("--name", default=None,
              help="fleet name; replicas deploy as NAME-r0..N-1")
@click.option("--registry", "registry_dir", type=click.Path(), default=None)
@click.option("--affinity/--no-affinity", default=True, show_default=True,
              help="route by consistent hash of the prompt's leading "
                   "token blocks so shared prefixes reuse one replica's "
                   "radix KV cache")
@click.option("--block", type=int, default=32, show_default=True,
              help="affinity block width in tokens — keep equal to the "
                   "bundle's prefix_block")
@click.option("--probe-interval", type=float, default=1.0, show_default=True,
              help="seconds between /healthz probes per replica")
@click.option("--fail-threshold", type=int, default=1, show_default=True,
              help="consecutive probe/connect failures before ejection")
@click.option("--readmit-passes", type=int, default=2, show_default=True,
              help="consecutive probe passes before an ejected replica "
                   "takes traffic again")
@click.option("--retries", type=int, default=2, show_default=True,
              help="max re-sends of a request onto different replicas")
@click.option("--saturation", type=int, default=8, show_default=True,
              help="outstanding requests at which the affinity target is "
                   "bypassed for the least-loaded replica")
@click.option("--hedge", default="off", show_default=True,
              help="duplicate slow non-streamed requests on a second "
                   "replica: 'off', 'p95' (the router's observed P95), "
                   "or a fixed threshold in ms")
@click.option("--timeout", type=float, default=300.0, show_default=True,
              help="per-replica deploy ready timeout (seconds)")
@click.option("--engine-watchdog", type=float, default=None,
              help="per-replica engine watchdog in seconds (see "
                   "`lambdipy serve --engine-watchdog`): a replica "
                   "whose device wait hangs flips its /healthz to "
                   "wedged and the pool ejects it at probe speed")
@click.option("--attach", "attach_urls", multiple=True,
              metavar="NAME=URL[:class]",
              help="attach an externally managed replica (remote host "
                   "or existing deployment): probed/ejected/readmitted/"
                   "cache-warmed like spawned ones, but never restarted "
                   "or drained by this pool; repeatable, and with "
                   "--replicas 0 the fleet is attach-only. An optional "
                   ":class suffix (prefill|decode|mixed, default mixed) "
                   "sets the replica's phase-split class")
@click.option("--spill-cap", type=int, default=64, show_default=True,
              help="router spill-queue capacity: when the WHOLE fleet "
                   "sheds or nothing is routable, non-streamed requests "
                   "park here and drain as replicas recover instead of "
                   "relaying the 429/503 (0 disables)")
@click.option("--spill-max-wait", type=float, default=30.0,
              show_default=True,
              help="max seconds a spilled request waits before shedding "
                   "with the queue's own Retry-After estimate")
@click.option("--breaker-fails", type=int, default=5, show_default=True,
              help="consecutive forward failures that open a replica's "
                   "circuit breaker; after --breaker-open-s one "
                   "half-open probe decides readmission (0 disables)")
@click.option("--breaker-open-s", type=float, default=2.0,
              show_default=True,
              help="seconds a breaker stays open before its half-open "
                   "probe (doubles on repeated failures, capped)")
@click.option("--retry-budget", type=float, default=0.2, show_default=True,
              help="fleet-wide retry-to-primary ratio over a sliding "
                   "window: when spent, failures relay instead of "
                   "re-sending — no retry storms into a degraded fleet "
                   "(0 disables)")
@click.option("--fault-spec", default=None,
              help="router-side network fault injection "
                   "(runtime/faults.py grammar over the route_connect/"
                   "route_body/route_latency/probe sites), default "
                   "$LAMBDIPY_FLEET_FAULT")
@click.option("--session-pin-budget", type=float, default=None,
              help="per-replica MB of prefix-cache KV open multi-turn "
                   "sessions may pin (see `lambdipy serve "
                   "--session-pin-budget`); the router routes sessions "
                   "STICKY to the replica holding their pinned KV and "
                   "re-ships it on failover")
@click.option("--session-ttl", type=float, default=None,
              help="per-replica absolute session pin lease in seconds "
                   "(see `lambdipy serve --session-ttl`)")
@click.option("--ship-window", type=int, default=4, show_default=True,
              help="pipelined KV shipping: max chunk frames in flight "
                   "between the export and import legs of a phase-split "
                   "ship (each flushed as its prefill chunk completes, "
                   "so cross-host transfer hides under the remaining "
                   "prefill); 0 = the blocking single-frame ship")
@click.option("--autoscale/--no-autoscale", default=False, show_default=True,
              help="close the control loop: a FleetController scrapes "
                   "the fleet's own /metrics and promotes/demotes "
                   "replica classes, spawns/retires replicas, and "
                   "retunes pipeline_depth/spec_k/ship-window from the "
                   "published signals (hysteresis + cooldown built in; "
                   "decisions trace under fleet.controller in /metrics)")
@click.option("--autoscale-dry-run", is_flag=True, default=False,
              help="run the control loop but only LOG decisions as "
                   "intents — no lifecycle action or knob write fires; "
                   "the recommended first step in a new deployment")
@click.option("--slo-p99-ms", type=float, default=250.0, show_default=True,
              help="autoscale target: fleet-level interactive queue-wait "
                   "P99 the controller steers toward")
@click.option("--autoscale-interval", type=float, default=5.0,
              show_default=True,
              help="seconds between controller ticks (scrape + decide)")
def fleet_cmd(bundle, replicas, prefill_replicas, port, name, registry_dir,
              affinity, block, probe_interval, fail_threshold,
              readmit_passes, retries, saturation, hedge, timeout,
              engine_watchdog, attach_urls, spill_cap, spill_max_wait,
              breaker_fails, breaker_open_s, retry_budget, fault_spec,
              session_pin_budget, session_ttl, ship_window, autoscale,
              autoscale_dry_run, slo_p99_ms, autoscale_interval):
    """Serve a bundle from N supervised replicas behind one router.

    Spawns REPLICAS watchdogged deployments of BUNDLE, health-probes
    them (eject on failure, re-admit on recovery), and serves
    /v1/completions + /invoke on PORT with prefix-affinity routing,
    failover retries, and fleet-wide /metrics. With --prefill-replicas
    (or an --attach :prefill class) the fleet serves DISAGGREGATED:
    cold prefills run on the prefill class, their KV blocks ship to the
    affinity-chosen decode replica, and any ship failure falls back to
    mixed-mode local prefill."""
    import signal as _signal
    import threading as _threading

    from lambdipy_tpu.fleet import (
        DECODE,
        MIXED,
        PREFILL,
        FleetError,
        FleetRouter,
        ReplicaPool,
        parse_attach_spec,
    )
    from lambdipy_tpu.runtime.deploy import LocalRuntime
    from lambdipy_tpu.runtime.faults import FaultPlan

    if replicas < 1 and not attach_urls:
        raise click.ClickException(
            "--replicas must be >= 1 (or pass --attach for an "
            "attach-only fleet)")
    if prefill_replicas < 0:
        raise click.ClickException("--prefill-replicas must be >= 0")
    attached: list[tuple[str, str, str]] = []
    for spec in attach_urls:
        try:
            attached.append(parse_attach_spec(spec))
        except FleetError as e:
            raise click.ClickException(str(e))
    try:
        fleet_faults = (FaultPlan.from_spec(fault_spec)
                        if fault_spec is not None
                        else FaultPlan.from_env(var="LAMBDIPY_FLEET_FAULT"))
    except ValueError as e:
        raise click.ClickException(str(e))
    hedge_ms: float | str = 0
    if hedge not in ("off", "0", ""):
        if hedge == "p95":
            hedge_ms = "p95"
        else:
            try:
                hedge_ms = float(hedge)
            except ValueError:
                raise click.ClickException(
                    f"--hedge must be 'off', 'p95' or a threshold in "
                    f"ms, got {hedge!r}")
    # an attach-only fleet (--replicas 0) never deploys the bundle, so
    # don't require it to resolve locally
    bundle_dir = (_resolve_bundle(bundle, registry_dir)
                  if replicas >= 1 or prefill_replicas >= 1 else None)
    fleet_name = name or bundle.split("/")[-1]
    pool = ReplicaPool(probe_interval=probe_interval,
                       fail_threshold=fail_threshold,
                       readmit_passes=readmit_passes,
                       faults=fleet_faults)
    replica_env = {}
    if engine_watchdog is not None:
        replica_env["LAMBDIPY_ENGINE_WATCHDOG_S"] = str(engine_watchdog)
    if session_pin_budget is not None:
        replica_env["LAMBDIPY_SESSION_PIN_BUDGET_MB"] = \
            str(session_pin_budget)
    if session_ttl is not None:
        replica_env["LAMBDIPY_SESSION_TTL_S"] = str(session_ttl)
    replica_env = replica_env or None
    spawned = []
    try:
        runtime = LocalRuntime()
        if replicas >= 1:
            # with a prefill class configured, the serve replicas are
            # DECODE-class (the phase split is the point); otherwise
            # they stay mixed and the fleet behaves exactly as before
            spawned = pool.spawn_fleet(
                bundle_dir, replicas, base_name=fleet_name,
                runtime=runtime, env=replica_env, ready_timeout=timeout,
                role=(DECODE if prefill_replicas else MIXED))
        for i in range(prefill_replicas):
            spawned.append(pool.spawn(
                f"{fleet_name}-p{i}", bundle_dir, runtime=runtime,
                env=replica_env, ready_timeout=timeout, role=PREFILL))
        for aname, aurl, arole in attached:
            pool.probe_one(pool.attach(aname, aurl, role=arole))
        pool.start()
        # inside the same guard: a router bind failure (port in use)
        # must not leak N supervised replica processes
        router = FleetRouter(pool, port=port, affinity_on=affinity,
                             block=block, max_retries=retries,
                             saturation=saturation, hedge_ms=hedge_ms,
                             spill_cap=spill_cap,
                             spill_max_wait_s=spill_max_wait,
                             breaker_fails=breaker_fails,
                             breaker_open_s=breaker_open_s,
                             retry_budget=retry_budget,
                             ship_window=ship_window,
                             faults=fleet_faults)
        controller = None
        if autoscale or autoscale_dry_run:
            from lambdipy_tpu.fleet import FleetController, PolicyConfig

            spawner = None
            if bundle_dir is not None:
                counter = iter(range(len(spawned), 10_000))

                def spawner(role):
                    nm = f"{fleet_name}-a{next(counter)}"
                    pool.spawn(nm, bundle_dir, runtime=runtime,
                               env=replica_env, ready_timeout=timeout,
                               role=role)
                    return nm

            controller = FleetController(
                router,
                config=PolicyConfig(slo_p99_ms=slo_p99_ms),
                interval_s=autoscale_interval,
                dry_run=autoscale_dry_run,
                spawner=spawner).start()
    except BaseException:
        # a half-spawned fleet must not leak processes — including on
        # Ctrl-C, which lands mid-boot more often than anywhere else
        # (each replica's cold start can take minutes)
        pool.stop_all()
        raise
    click.echo(json.dumps({
        "ready": True, "port": router.port, "replicas": len(spawned),
        "prefill_replicas": prefill_replicas,
        "attached": [a for a, _, _ in attached],
        "classes": {r.name: r.role
                    for r in pool.replicas.values()},
        "affinity": affinity, "block": block,
        "spill_cap": spill_cap, "breaker_fails": breaker_fails,
        "retry_budget": retry_budget,
        "autoscale": bool(autoscale or autoscale_dry_run),
        "autoscale_dry_run": bool(autoscale_dry_run),
        "slo_p99_ms": slo_p99_ms,
        "urls": {r.name: r.url for r in spawned},
    }))

    def _term(signum, frame):
        _threading.Thread(target=router.stop, daemon=True).start()

    _signal.signal(_signal.SIGTERM, _term)
    try:
        router.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        if controller is not None:
            controller.close()
        pool.stop_all()


@main.command("invoke")
@click.argument("name")
@click.option("--data", default="{}", help="JSON request body")
@click.option("--stream", is_flag=True,
              help="stream the response (generate handlers): one JSON "
                   "line per decode segment as tokens are emitted")
def invoke_cmd(name, data, stream):
    """Invoke a deployed function."""
    from lambdipy_tpu.runtime.deploy import DeployError, LocalRuntime

    try:
        request = json.loads(data)
    except json.JSONDecodeError as e:
        raise click.ClickException(f"--data is not valid JSON: {e}") from e
    try:
        if stream:
            for chunk in LocalRuntime().invoke_stream(name, request):
                click.echo(json.dumps(chunk))
        else:
            click.echo(json.dumps(LocalRuntime().invoke(name, request)))
    except DeployError as e:
        raise click.ClickException(str(e)) from e


@main.command("deployments")
def deployments_cmd():
    """List deployments."""
    from lambdipy_tpu.runtime.deploy import LocalRuntime

    for dep in LocalRuntime().list():
        click.echo(f"{dep.name:25s} pid={dep.pid:<8d} {dep.url}")


@main.command("doctor")
@click.option("--registry", "registry_dir", type=click.Path(), default=None)
@click.option("--state", "state_path", type=click.Path(), default=None,
              help="deployments state file (default: ~/.lambdipy-tpu)")
@click.option("--probe-timeout", default=90.0, show_default=True,
              help="seconds before the device probe is declared wedged")
def doctor_cmd(registry_dir, state_path, probe_timeout):
    """Environment diagnostics: stack versions, device reachability (the
    TPU transport can wedge indefinitely — the probe is a subprocess with
    a timeout, never an in-process jax.devices()), registry and
    deployment health. Prints one JSON object; exit 1 if the device probe
    fails while the shell is configured for a device platform."""
    import importlib.metadata as md
    import os
    import subprocess

    from lambdipy_tpu.resolve.registry import ArtifactRegistry
    from lambdipy_tpu.runtime.deploy import LocalRuntime

    report: dict = {"python": sys.version.split()[0]}
    report["packages"] = {}
    for pkg in ("jax", "jaxlib", "libtpu", "flax", "optax", "orbax-checkpoint"):
        try:
            report["packages"][pkg] = md.version(pkg)
        except md.PackageNotFoundError:
            report["packages"][pkg] = None

    probe_env = dict(os.environ)
    repo_root = str(Path(__file__).resolve().parents[1])
    probe_env["PYTHONPATH"] = os.pathsep.join(
        [repo_root] + [p for p in probe_env.get("PYTHONPATH", "").split(os.pathsep) if p])
    try:
        proc = subprocess.run(
            [sys.executable, "-c",
             # the one place LAMBDIPY_PLATFORM is honored is the shared
             # helper — the probe must diagnose the same environment the
             # real entry points run in. LAMBDIPY_DOCTOR_WEDGE is fault
             # injection (the bench.py pattern): tests prove the
             # timeout->diagnosis path without betting on a slow tunnel
             "import os, time\n"
             "if os.environ.get('LAMBDIPY_DOCTOR_WEDGE'): time.sleep(3600)\n"
             "from lambdipy_tpu.utils.platform import apply_platform_override\n"
             "apply_platform_override()\n"
             "import jax\n"
             "d = jax.devices()\n"
             "print('DOCTOR', d[0].platform, len(d))"],
            capture_output=True, text=True, env=probe_env,
            timeout=probe_timeout)
        # parse only our marker line: sitecustomize/plugins may write
        # banners to the child's stdout
        marker = [ln for ln in proc.stdout.splitlines()
                  if ln.startswith("DOCTOR ")]
        if proc.returncode == 0 and marker:
            _, platform, n = marker[-1].split()
            report["device"] = {"ok": True, "platform": platform,
                                "n_devices": int(n)}
        else:
            report["device"] = {"ok": False,
                                "error": proc.stderr.strip()[-300:]}
    except subprocess.TimeoutExpired:
        report["device"] = {
            "ok": False,
            "error": f"wedge: device enumeration hung for {probe_timeout:.0f}s "
                     "(transport down? another process holding the device?)"}

    try:
        arts = ArtifactRegistry(registry_dir).list()
        report["registry"] = {"artifacts": len(arts),
                              "bytes": sum(a.size_bytes for a in arts)}
    except Exception as e:
        report["registry"] = {"error": str(e)}
    deployments = []
    try:
        rt = LocalRuntime(Path(state_path) if state_path else None)
        for dep in rt.list():
            entry = {"name": dep.name, "url": dep.url}
            try:
                entry["healthy"] = bool(rt.health(dep.name).get("ok"))
            except Exception as e:
                entry["healthy"] = False
                entry["error"] = str(e)[:120]
            deployments.append(entry)
    except Exception as e:
        deployments = [{"error": str(e)[:120]}]
    report["deployments"] = deployments

    click.echo(json.dumps(report, indent=1))
    effective = (os.environ.get("LAMBDIPY_PLATFORM")
                 or os.environ.get("JAX_PLATFORMS", ""))
    if not report["device"]["ok"] and effective not in ("", "cpu"):
        raise SystemExit(1)


@main.command("train")
@click.option("--model", "model_name", default="llama-tiny",
              help="registry model (llama-tiny / llama3-8b / llama-moe-tiny ...)")
@click.option("--data", "data_path", type=click.Path(exists=True), required=True,
              help="token file (.npy or raw int32 binary)")
@click.option("--steps", type=int, default=100)
@click.option("--batch", "global_batch", type=int, default=8)
@click.option("--seq-len", type=int, default=128)
@click.option("--lr", type=float, default=1e-3)
@click.option("--ckpt-dir", type=click.Path(), default=None,
              help="checkpoint dir; re-running resumes from the latest step")
@click.option("--ckpt-every", type=int, default=50)
@click.option("--mesh", "mesh_spec", default=None,
              help='mesh axes, e.g. "dp=2,tp=2" (default: all devices on dp)')
@click.option("--seed", type=int, default=0)
def train_cmd(model_name, data_path, steps, global_batch, seq_len, lr,
              ckpt_dir, ckpt_every, mesh_spec, seed):
    """Train a registry model on a token file (resumable SPMD loop)."""
    import jax

    from lambdipy_tpu.data import ShardedLoader, TokenSource
    from lambdipy_tpu.models import registry as model_registry
    from lambdipy_tpu.parallel.distributed import initialize_from_env
    from lambdipy_tpu.parallel.mesh import make_mesh, use_mesh
    from lambdipy_tpu.train.loop import Trainer, TrainerConfig

    initialize_from_env()
    adapter = model_registry.get(model_name).build()
    params = adapter.init_params(seed=seed)
    if mesh_spec:
        shape = {}
        for part in mesh_spec.split(","):
            axis, eq, size = part.partition("=")
            try:
                if not eq:
                    raise ValueError("missing '='")
                shape[axis.strip()] = int(size)
            except ValueError as e:
                raise click.ClickException(
                    f"bad --mesh entry {part!r} (want axis=size, e.g. "
                    f"dp=2,tp=4): {e}") from e
        if any(v == -1 for v in shape.values()):
            devices = jax.devices()  # -1 fills: make_mesh needs them all
        else:
            needed = 1
            for v in shape.values():
                needed *= v
            devices = jax.devices()[:needed]
        mesh = make_mesh(shape, devices=devices)
    else:
        mesh = make_mesh({"dp": len(jax.devices())})
    loader = ShardedLoader(TokenSource(data_path, seq_len), global_batch,
                           seed=seed)
    cfg = TrainerConfig(total_steps=steps, learning_rate=lr,
                        ckpt_every=ckpt_every)
    with use_mesh(mesh):
        with Trainer(adapter.forward, params, mesh, adapter.tp_rules,
                     loader, cfg, ckpt_dir=ckpt_dir,
                     model_apply_aux=adapter.forward_with_aux) as trainer:
            report = trainer.run()
    last = report.history[-1] if report.history else {}
    click.echo(json.dumps({
        "model": model_name, "final_step": report.final_step,
        "steps_run": report.steps_run, "resumed_from": report.resumed_from,
        "mesh": dict(mesh.shape), "final_metrics": last,
    }))


@main.command("bench")
@click.argument("name")
@click.option("--data", default='{"random": true}', help="JSON request body")
@click.option("-n", "iters", type=int, default=50, help="measured invokes")
@click.option("--warmup", type=int, default=5)
def bench_cmd(name, data, iters, warmup):
    """Measure invoke latency percentiles against a deployment."""
    import statistics
    import time as _time

    from lambdipy_tpu.runtime.deploy import DeployError, LocalRuntime

    try:
        request = json.loads(data)
    except json.JSONDecodeError as e:
        raise click.ClickException(f"--data is not valid JSON: {e}") from e
    rt = LocalRuntime()
    try:
        for _ in range(warmup):
            rt.invoke(name, request)
        times = []
        for _ in range(iters):
            t0 = _time.monotonic()
            out = rt.invoke(name, request)
            times.append((_time.monotonic() - t0) * 1000.0)
            if not out.get("ok", True):
                raise click.ClickException(f"invoke failed: {out}")
    except DeployError as e:
        raise click.ClickException(str(e)) from e
    times.sort()

    def pct(q):  # nearest-rank percentile: ceil(q*n) - 1, 0-based
        return times[max(0, math.ceil(q * iters) - 1)]

    click.echo(json.dumps({
        "name": name, "n": iters,
        "p50_ms": round(statistics.median(times), 3),
        "p90_ms": round(pct(0.90), 3),
        "p99_ms": round(pct(0.99), 3),
        "mean_ms": round(statistics.fmean(times), 3),
    }))


@main.command("stop")
@click.argument("name")
def stop_cmd(name):
    """Stop a deployment."""
    from lambdipy_tpu.runtime.deploy import DeployError, LocalRuntime

    try:
        LocalRuntime().stop(name)
    except DeployError as e:
        raise click.ClickException(str(e)) from e
    click.echo(f"stopped {name}")


if __name__ == "__main__":
    main()
