"""Tracing/profiling (SURVEY.md §6: absent in the reference; first-class
here).

Two layers:
- :func:`profile_trace` — ``jax.profiler`` capture to a directory, viewable
  with tensorboard-plugin-profile (the canonical TPU stack per the
  jax-stable-stack image, SURVEY.md §3.4 ``jss:tpu/Dockerfile:94``). Used
  by the serve loop's ``/profile`` endpoint and ad-hoc by benchmarks.
- build/serve stage timing — :class:`lambdipy_tpu.utils.timing.StageTimer`
  records per-stage wall time into manifests and /healthz.
"""

from __future__ import annotations

import time
from contextlib import contextmanager
from pathlib import Path


class TraceCapture:
    """Handle yielded by :func:`profile_trace`; ``started`` records whether
    the profiler actually engaged (callers must surface this — an untraced
    capture must not masquerade as a trace)."""

    def __init__(self, out_dir: Path):
        self.out_dir = Path(out_dir)
        self.started = False
        self.error: str | None = None


@contextmanager
def profile_trace(out_dir: Path):
    """Capture a jax profiler trace into ``out_dir`` (xplane protos +
    trace.json.gz). Never raises — serving must not die to tracing — but
    the yielded :class:`TraceCapture` reports whether the profiler engaged
    (it won't if jax is absent or another trace is already active)."""
    capture = TraceCapture(out_dir)
    capture.out_dir.mkdir(parents=True, exist_ok=True)
    try:
        import jax

        jax.profiler.start_trace(str(capture.out_dir))
        capture.started = True
    except Exception as e:
        capture.error = f"{type(e).__name__}: {e}"
    t0 = time.monotonic()
    try:
        yield capture
    finally:
        if capture.started:
            try:
                import jax

                jax.profiler.stop_trace()
            except Exception as e:
                capture.error = f"stop_trace: {type(e).__name__}: {e}"
        (capture.out_dir / "capture_meta.json").write_text(
            '{"wall_s": %.4f, "started": %s}'
            % (time.monotonic() - t0, "true" if capture.started else "false"))


def latest_trace_files(out_dir: Path) -> list[str]:
    out_dir = Path(out_dir)
    if not out_dir.is_dir():
        return []
    return sorted(str(p.relative_to(out_dir))
                  for p in out_dir.rglob("*") if p.is_file())[:50]
