"""Decode sampling: logit filtering, temperature/top-k/top-p generation,
eos short-circuit."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from lambdipy_tpu.models import registry
from lambdipy_tpu.models.llama import filter_logits, greedy_generate, sample_generate


def test_filter_logits_top_k():
    logits = jnp.asarray([[1.0, 3.0, 2.0, 0.0]], jnp.float32)
    out = filter_logits(logits, top_k=2)
    probs = np.asarray(jax.nn.softmax(out, axis=-1))[0]
    assert probs[1] > 0 and probs[2] > 0
    np.testing.assert_allclose(probs[0] + probs[3], 0.0, atol=1e-6)


def test_filter_logits_top_p():
    # probs ~ [0.643, 0.237, 0.087, 0.032] — top_p=0.6 keeps only the head;
    # top_p=0.7 keeps two (cumulative-before-token rule)
    logits = jnp.asarray([[4.0, 3.0, 2.0, 1.0]], jnp.float32)
    kept1 = np.asarray(jax.nn.softmax(filter_logits(logits, top_p=0.6)))[0]
    assert kept1[0] > 0.999
    kept2 = np.asarray(jax.nn.softmax(filter_logits(logits, top_p=0.7)))[0]
    assert kept2[0] > 0 and kept2[1] > 0
    np.testing.assert_allclose(kept2[2] + kept2[3], 0.0, atol=1e-6)


def test_filter_logits_always_keeps_argmax():
    logits = jnp.asarray([[10.0, 0.0, 0.0, 0.0]], jnp.float32)
    out = filter_logits(logits, top_k=1, top_p=0.01)
    assert int(jnp.argmax(out)) == 0
    assert np.isfinite(np.asarray(out)[0, 0])


@pytest.fixture(scope="module")
def tiny_llama():
    adapter = registry.get("llama-tiny").build()
    return adapter, adapter.init_params(seed=0)


def test_sample_temperature_zero_is_greedy(tiny_llama):
    adapter, params = tiny_llama
    prompt = jnp.asarray([[5, 6, 7, 8]], jnp.int32)
    ref = greedy_generate(adapter.module, params, prompt, max_new_tokens=6)
    out = sample_generate(adapter.module, params, prompt,
                          rng=jax.random.PRNGKey(1), max_new_tokens=6,
                          temperature=0.0)
    np.testing.assert_array_equal(np.asarray(ref), np.asarray(out))


def test_sample_deterministic_per_key_and_varies(tiny_llama):
    adapter, params = tiny_llama
    prompt = jnp.asarray([[5, 6, 7, 8]], jnp.int32)

    def draw(seed):
        return np.asarray(sample_generate(
            adapter.module, params, prompt, rng=jax.random.PRNGKey(seed),
            max_new_tokens=8, temperature=1.5))

    np.testing.assert_array_equal(draw(0), draw(0))
    draws = [draw(s) for s in range(6)]
    assert any(not np.array_equal(draws[0], d) for d in draws[1:]), \
        "6 seeds at temperature 1.5 all produced identical tokens"


def test_sample_top_k1_is_greedy(tiny_llama):
    """top_k=1 collapses the categorical to argmax at any temperature."""
    adapter, params = tiny_llama
    prompt = jnp.asarray([[9, 10, 11]], jnp.int32)
    ref = greedy_generate(adapter.module, params, prompt, max_new_tokens=5)
    out = sample_generate(adapter.module, params, prompt,
                          rng=jax.random.PRNGKey(3), max_new_tokens=5,
                          temperature=2.0, top_k=1)
    np.testing.assert_array_equal(np.asarray(ref), np.asarray(out))


def test_eos_short_circuit(tiny_llama):
    """Once eos appears, the remainder of the row is eos."""
    adapter, params = tiny_llama
    prompt = jnp.asarray([[5, 6, 7, 8]], jnp.int32)
    free = np.asarray(greedy_generate(adapter.module, params, prompt,
                                      max_new_tokens=8))[0]
    eos = int(free[2])  # force the 3rd emitted token to be "eos"
    out = np.asarray(greedy_generate(adapter.module, params, prompt,
                                     max_new_tokens=8, eos_id=eos))[0]
    np.testing.assert_array_equal(out[:3], free[:3])
    assert (out[np.where(out == eos)[0][0]:] == eos).all()


def test_registry_generate_routes_sampling(tiny_llama):
    adapter, params = tiny_llama
    prompt = jnp.asarray([[1, 2, 3]], jnp.int32)
    greedy = adapter.generate(params, prompt, max_new_tokens=4)
    sampled = adapter.generate(params, prompt, max_new_tokens=4,
                               temperature=1.0, top_k=8, seed=7)
    assert np.asarray(greedy).shape == np.asarray(sampled).shape == (1, 4)


def test_filter_logits_top_p_zero_degrades_to_greedy():
    """top_p <= 0 keeps (only) the argmax instead of masking everything."""
    logits = jnp.asarray([[10.0, 0.0, -1.0, -2.0]], jnp.float32)
    out = np.asarray(filter_logits(logits, top_p=0.0))[0]
    assert out[0] == 10.0
    assert (out[1:] < -1e29).all()
