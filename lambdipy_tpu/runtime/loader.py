"""Bundle boot: manifest -> importable, warmed handler.

The cold-start path (SURVEY.md §4 D/E): every stage is timed because the
<10 s budget is consumed by interpreter + PJRT init + first compile
(BASELINE.md). The loader:

1. reads + verifies the manifest, checks base-layer version skew,
2. layers sys.path: bundle ``site/`` first, base layer (host site) after,
3. points JAX's persistent compilation cache at the bundle's
   ``compile_cache/`` (shipped warm by the builder -> first compile becomes
   a cache hit, SURVEY.md §9.6),
4. imports ``handler.py``, calls ``init(ctx)``, runs a warmup invoke.
"""

from __future__ import annotations

import importlib.util
import json
import sys
from dataclasses import dataclass, field
from pathlib import Path
from typing import Any

from lambdipy_tpu.bundle.baselayer import check_skew, runtime_sys_path
from lambdipy_tpu.bundle.format import load_manifest
from lambdipy_tpu.utils.logs import get_logger, log_event
from lambdipy_tpu.utils.timing import StageTimer

log = get_logger("lambdipy.runtime")


@dataclass
class HandlerContext:
    """What a bundle handler gets at init time."""

    bundle_dir: Path
    manifest: dict
    params_dir: Path | None
    spec: dict  # payload spec from the manifest

    def degraded(self) -> list[str]:
        return list(self.manifest.get("provenance", {}).get("skipped_optional", []))


@dataclass
class BootReport:
    bundle_dir: Path
    handler: Any
    state: Any
    stages: dict[str, float] = field(default_factory=dict)
    skew: dict = field(default_factory=dict)
    warmup_result: Any = None
    manifest: dict = field(default_factory=dict)
    # active numerics-sanitizer flags (utils/debug.py apply_debug_env);
    # non-empty means every jit call pays a device sync
    debug_flags: dict = field(default_factory=dict)

    def cold_start_s(self) -> float:
        return sum(self.stages.values())


def attach_compile_cache(bundle_dir: Path) -> bool:
    """Point JAX's persistent compilation cache at the bundle's cache dir
    (created if absent, so the first boot warms it for the next)."""
    cache_dir = Path(bundle_dir) / "compile_cache"
    try:
        import jax

        cache_dir.mkdir(parents=True, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", str(cache_dir))
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
        return True
    except Exception as e:  # non-jax bundles don't care
        log.warning("compile cache attach failed: %s", e)
        return False


def load_bundle(bundle_dir: Path, *, warmup: bool = True) -> BootReport:
    bundle_dir = Path(bundle_dir)
    timer = StageTimer()

    with timer.stage("manifest"):
        manifest = load_manifest(bundle_dir)
        payload = manifest.get("payload")
        if payload is None:
            raise ValueError(f"bundle {bundle_dir} has no payload; nothing to serve")
        base = manifest.get("base_layer", {"name": "none", "versions": {}})
        skew = check_skew(base.get("versions", {}), base.get("name", "none"))
        if skew:
            log_event(log, "base layer skew detected", skew=skew)

    with timer.stage("syspath"):
        site_dir = bundle_dir / "site"
        for p in reversed(runtime_sys_path(site_dir, base.get("name", "none"))):
            if p not in sys.path:
                sys.path.insert(0, p)

    with timer.stage("compile_cache"):
        from lambdipy_tpu.models import registry as model_registry

        try:
            uses_jax = model_registry.get(payload.get("model", "")).kind == "jax"
        except Exception:
            uses_jax = False
        if uses_jax:
            attach_compile_cache(bundle_dir)
            # start PJRT backend init NOW on a worker thread so the
            # device attach (0.1-6.5 s measured through the axon tunnel,
            # high variance) overlaps the handler import + params restore
            # below instead of serializing in front of them. Backend init
            # is lock-guarded inside jax; the handler's first device call
            # simply joins it.
            import threading

            def _init_backend():
                try:
                    import jax

                    jax.devices()
                except Exception as e:  # surfaced again, with context, by
                    log.warning("background PJRT init failed: %s", e)

            threading.Thread(target=_init_backend, daemon=True,
                             name="pjrt-init").start()
        from lambdipy_tpu.utils.debug import apply_debug_env

        # opt-in numerics sanitizer (LAMBDIPY_DEBUG_NANS=1 in the
        # deployment env): NaN/Inf in any jit output raises at the
        # producing primitive instead of poisoning responses. Applied
        # regardless of the registry-derived uses_jax flag — a custom
        # handler may use jax directly; without the env vars it is a
        # jax-free no-op
        debug_flags = apply_debug_env()

    with timer.stage("handler_import"):
        spec = importlib.util.spec_from_file_location(
            f"lambdipy_bundle_handler_{bundle_dir.name}", bundle_dir / "handler.py")
        module = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(module)

    with timer.stage("init"):
        params_dir = bundle_dir / "params"
        ctx = HandlerContext(
            bundle_dir=bundle_dir,
            manifest=manifest,
            params_dir=params_dir if params_dir.is_dir() else None,
            spec=dict(payload),
        )
        state = module.init(ctx)

    warmup_result = None
    if warmup:
        with timer.stage("warmup"):
            warmup_result = module.invoke(state, {"warmup": True})

    report = BootReport(
        bundle_dir=bundle_dir,
        handler=module,
        state=state,
        stages=timer.report(),
        skew=skew,
        warmup_result=warmup_result,
        manifest=manifest,
        debug_flags=debug_flags,
    )
    log_event(log, "bundle booted", bundle=str(bundle_dir),
              cold_start=report.stages, skew=bool(skew))
    return report
