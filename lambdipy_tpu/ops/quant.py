"""Int8 weight-only matmul: Pallas TPU kernel + pure-jax reference.

The HBM-bound op of quantized serving (models/llama.py QDense): weights
live in HBM as int8 + per-output-channel fp32 scales (1 byte/param of
traffic), tiles are upcast to bf16 in VMEM so the MXU still does bf16
math, and the fp32 accumulator is scaled once at finalize. Grid is
(m_blocks, n_blocks, k_blocks) with k innermost — TPU grid execution is
sequential, so the f32 scratch accumulator carries across k steps (same
pattern as ops/attention.py).

The pure-jax ``int8_matmul_reference`` is the numerics oracle and the
CPU/odd-shape fallback.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def int8_matmul_reference(x, w_i8, scale):
    """x: [m, k] (bf16/f32); w_i8: [k, n] int8; scale: [1, n] f32.
    Returns [m, n] in x.dtype: (x @ dequant(w)) with per-channel scales."""
    w = w_i8.astype(jnp.bfloat16) * scale.astype(jnp.bfloat16)
    return (x.astype(jnp.bfloat16) @ w).astype(x.dtype)


def _kernel(x_ref, w_ref, s_ref, o_ref, acc_ref, *, n_k: int):
    @pl.when(pl.program_id(2) == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    xb = x_ref[...].astype(jnp.bfloat16)
    wb = w_ref[...].astype(jnp.bfloat16)  # int8 -> bf16 upcast in VMEM
    acc_ref[...] += jnp.dot(xb, wb, preferred_element_type=jnp.float32)

    @pl.when(pl.program_id(2) == n_k - 1)
    def _finalize():
        o_ref[...] = (acc_ref[...] * s_ref[...].astype(jnp.float32)).astype(
            o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("block_m", "block_n", "block_k",
                                             "interpret"))
def int8_matmul(x, w_i8, scale, *, block_m: int = 128, block_n: int = 128,
                block_k: int = 128, interpret: bool | None = None):
    """Blocked int8-weight matmul. Falls back to the reference when shapes
    don't tile (serving decode has m as small as 1) or on CPU without
    interpret mode. ``interpret=None`` auto-selects interpret on CPU."""
    if interpret is None:
        interpret = jax.default_backend() == "cpu"
    m, k = x.shape
    k2, n = w_i8.shape
    assert k == k2 and scale.shape == (1, n), (x.shape, w_i8.shape, scale.shape)
    block_m = min(block_m, m)
    if m % block_m or n % block_n or k % block_k:
        return int8_matmul_reference(x, w_i8, scale)
    n_k = k // block_k
    return pl.pallas_call(
        functools.partial(_kernel, n_k=n_k),
        grid=(m // block_m, n // block_n, n_k),
        in_specs=[
            pl.BlockSpec((block_m, block_k), lambda i, j, kk: (i, kk)),
            pl.BlockSpec((block_k, block_n), lambda i, j, kk: (kk, j)),
            pl.BlockSpec((1, block_n), lambda i, j, kk: (0, j)),
        ],
        out_specs=pl.BlockSpec((block_m, block_n), lambda i, j, kk: (i, j)),
        out_shape=jax.ShapeDtypeStruct((m, n), x.dtype),
        scratch_shapes=[pltpu.VMEM((block_m, block_n), jnp.float32)],
        interpret=interpret,
    )(x, w_i8, scale)
