"""Measure BASELINE.json's staged configs through the REAL serve path and
record the numbers into ``BASELINE.json.published`` (SURVEY.md §5.3 /
VERDICT r2 missing #2: the suite never exercised the chip and ``published``
stayed empty).

Per config: ``lambdipy build <recipe>`` -> LocalRuntime.deploy (boot = the
actual cold start, through the supervisor + HTTP server) -> N timed
``/invoke`` round-trips -> p50/p99 + cold-start seconds. Configs 1-2 are
CPU configs and always run; configs 3-4 run on the TPU when it is
reachable (the axon tunnel on this image can wedge — a probe subprocess
guards every device config); config 5 needs a v5e-4 and records its
multi-chip evidence from the CPU-mesh dryrun instead.

Usage: python scripts/measure_baseline.py [--configs 1,2] [--invokes 30]
The tpu-marked tests (tests/test_tpu.py) call the same machinery and
assert the north-star budgets.
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import subprocess
import sys
import tempfile
import time
from pathlib import Path

REPO = Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO))

CONFIGS = {
    1: {"recipe": "hello-numpy", "platform": "cpu",
        "request": {"n": 64, "seed": 1}},
    2: {"recipe": "tabular-sklearn", "platform": "cpu",
        "request": {"instances": [[0.1] * 16]}},
    3: {"recipe": "jax-resnet50", "platform": "device",
        "request": {"random": True}},
    4: {"recipe": "jax-bert", "platform": "device",
        "request": {"input_ids": [[101, 2054, 2003, 102]]}},
    # config 5 exemplar: the 8B recipe needs a v5e-4; this is the same
    # int8 + compile-once-decode serve path at single-chip scale. The
    # multi-chip sharding evidence for the full recipe is the CPU-mesh
    # dryrun (__graft_entry__.dryrun_multichip).
    5: {"recipe": "jax-llama-micro", "platform": "device",
        "request": {"tokens": [[1, 2, 3, 4, 5, 6, 7, 8]],
                    "max_new_tokens": 32}},
    # config 4's literal "pytorch recipe" path: torch-xla has no wheel in
    # this offline env, so the bundle degrades to the documented CPU-torch
    # smoke (the jax path above is the full-TPU sibling). Recorded as
    # "config4_torch" so both halves of config 4 carry measurements.
    "4t": {"recipe": "torch-xla-bert", "platform": "cpu",
           "request": {"input_ids": [[101, 2054, 2003, 102]]}},
}


def measure_d2h_floor(timeout_s: float = 180.0) -> float | None:
    """Median wall-clock ms to fetch a FRESH device result host-side.

    On a locally attached chip this is sub-millisecond (PCIe). Through a
    remote-tunnel PJRT plugin (the axon plugin this image uses) every
    fetch of a not-yet-transferred buffer pays one network round trip —
    measured ~66 ms here, independent of payload size down to a scalar,
    while host->device stays sub-ms. That RTT is a property of the test
    environment's transport, not of the serving stack: any synchronous
    invoke whose response depends on device output is bounded below by
    it. Recording the floor lets the device tests assert the north-star
    budget on serve-path overhead NET of transport, which converges to
    the plain end-to-end assertion on real hardware where the floor is
    ~0. Returns None if the probe fails (no device / wedge).
    """
    code = (
        "import json, statistics, time\n"
        "import jax, jax.numpy as jnp\n"
        "f = jax.jit(lambda x: (x * 2).sum())\n"
        "x = jax.device_put(jnp.ones((8, 8), jnp.float32))\n"
        "float(f(x))\n"
        "ts = []\n"
        "for _ in range(15):\n"
        "    t = time.monotonic(); float(f(x))\n"
        "    ts.append((time.monotonic() - t) * 1e3)\n"
        "print(json.dumps({'d2h_ms': round(statistics.median(ts), 3)}))\n"
    )
    try:
        proc = subprocess.run(
            [sys.executable, "-c", code], capture_output=True, text=True,
            timeout=timeout_s,
            env={k: v for k, v in os.environ.items()
                 if k != "LAMBDIPY_PLATFORM"})
        if proc.returncode != 0:
            return None
        return float(json.loads(proc.stdout.strip().splitlines()[-1])["d2h_ms"])
    except (subprocess.TimeoutExpired, ValueError, KeyError, IndexError):
        return None


def tpu_reachable(timeout_s: float = 90.0) -> bool:
    """Probe the device in a subprocess — jax.devices() can wedge."""
    try:
        proc = subprocess.run(
            [sys.executable, "-c",
             "import jax; assert jax.devices()[0].platform != 'cpu'"],
            capture_output=True, timeout=timeout_s,
            env={k: v for k, v in os.environ.items()
                 if k != "LAMBDIPY_PLATFORM"})
        return proc.returncode == 0
    except subprocess.TimeoutExpired:
        return False


def measure_config(num: int, *, invokes: int = 30,
                   work: Path | None = None,
                   d2h_floor: float | None = None) -> dict:
    """Build + deploy + invoke one config; returns the measured record.

    For device configs the record carries the environment's measured
    ``d2h_rtt_ms`` transport floor (see :func:`measure_d2h_floor`) and
    ``serve_overhead_p50_ms`` = p50 net of that floor — the number the
    serving stack is actually accountable for."""
    from lambdipy_tpu.runtime.deploy import LocalRuntime

    cfg = CONFIGS[num]
    work = Path(work or tempfile.mkdtemp(prefix=f"baseline-c{num}-"))
    bundle = work / "bundle"
    env = dict(os.environ)
    if cfg["platform"] == "cpu":
        env["LAMBDIPY_PLATFORM"] = "cpu"
    build_cmd = [sys.executable, "-m", "lambdipy_tpu", "build", cfg["recipe"],
                 "--out", str(bundle)]
    t0 = time.monotonic()
    proc = subprocess.run(build_cmd, capture_output=True, text=True, env=env,
                          cwd=str(REPO), timeout=1800)
    if proc.returncode != 0:
        raise RuntimeError(f"build failed: {proc.stderr[-500:]}")
    build_s = time.monotonic() - t0

    rt = LocalRuntime(work / "deployments.json")
    dep_env = ({"LAMBDIPY_PLATFORM": "cpu"}
               if cfg["platform"] == "cpu" else None)
    name = f"baseline-c{num}"
    t0 = time.monotonic()
    rt.deploy(name, bundle, env=dep_env)
    deploy_wall_s = time.monotonic() - t0
    try:
        health = rt.health(name)
        # warmup invokes are excluded from the latency sample
        for _ in range(3):
            rt.invoke(name, dict(cfg["request"]))
        times = []
        for _ in range(invokes):
            t = time.monotonic()
            out = rt.invoke(name, dict(cfg["request"]))
            times.append((time.monotonic() - t) * 1000.0)
            assert out.get("ok"), out
        times.sort()
        # the cold_start stage dict carries its own "total"; summing every
        # value would double-count it against the component stages
        cs = health["cold_start"]
        cold_start_s = cs.get("total", sum(v for k, v in cs.items()
                                           if k != "total"))
        record = {
            "recipe": cfg["recipe"],
            "platform": health.get("handler_meta", {}).get("platform",
                                                           cfg["platform"]),
            # e.g. config4_torch: the handler flags its degraded CPU path
            # so the published number can never read as a TPU number
            **({"degraded": health["handler_meta"]["degraded"]}
               if health.get("handler_meta", {}).get("degraded") else {}),
            "invoke_p50_ms": round(statistics.median(times), 3),
            "invoke_p99_ms": round(times[min(len(times) - 1,
                                             int(len(times) * 0.99))], 3),
            "cold_start_s": round(cold_start_s, 2),
            "deploy_wall_s": round(deploy_wall_s, 2),
            "build_s": round(build_s, 1),
            "n_invokes": invokes,
            "warm_ok": bool((health.get("warm") or {}).get("ok")),
            "measured_at": time.strftime("%Y-%m-%d"),
        }
        if cfg["platform"] == "device":
            if d2h_floor is None:
                d2h_floor = measure_d2h_floor()
            if d2h_floor is not None:
                record["d2h_rtt_ms"] = round(d2h_floor, 3)
                record["serve_overhead_p50_ms"] = round(
                    max(0.0, record["invoke_p50_ms"] - d2h_floor), 3)
        n_new = cfg["request"].get("max_new_tokens")
        if n_new:
            # decode throughput, net of the transport floor when known
            net_ms = record.get("serve_overhead_p50_ms",
                                record["invoke_p50_ms"])
            if net_ms > 0:
                record["decode_tok_s"] = round(n_new / (net_ms / 1e3), 1)
        _attach_roofline(record, cfg, n_new)
    finally:
        rt.stop(name)
    return record


def _attach_roofline(record: dict, cfg: dict, n_new: int | None) -> None:
    """Relate the measured number to v5e peak (VERDICT r3 missing #2):
    mfu/hbm_util for the ResNet north star and per-token decode
    utilization for the Llama configs, computed from the recipe's own
    dims (read from its TOML, so the record can never drift from what
    was actually served)."""
    from lambdipy_tpu.utils import roofline
    from lambdipy_tpu.utils.toml_compat import tomllib

    measured_ms = record.get("serve_overhead_p50_ms",
                             record.get("invoke_p50_ms", 0))
    if not measured_ms or record.get("platform") == "cpu":
        return
    if cfg["recipe"] == "jax-resnet50":
        cost = roofline.resnet50_cost(batch=1)
        record.update({k: v for k, v in
                       cost.utilization(measured_ms / 1e3).items()
                       if k in ("mfu", "hbm_util", "roofline_ms")})
    elif cfg["recipe"].startswith("jax-llama") and n_new:
        path = (REPO / "lambdipy_tpu" / "recipes" / "builtin"
                / f"{cfg['recipe']}.toml")
        rec = tomllib.loads(path.read_text())
        payload = rec["payload"]
        extra = payload.get("extra", {})
        from lambdipy_tpu.models.llama import LLAMA3_8B
        import dataclasses

        fields = {f.name for f in dataclasses.fields(LLAMA3_8B)}
        lcfg = dataclasses.replace(
            LLAMA3_8B, quant=payload.get("quant"),
            **{k: v for k, v in extra.items() if k in fields})
        prompt_len = len(cfg["request"]["tokens"][0])
        cost = roofline.llama_decode_step_cost(
            lcfg, batch=1, cache_len=prompt_len + n_new // 2)
        per_tok_s = measured_ms / n_new / 1e3
        record["dims"] = f"{lcfg.hidden}x{lcfg.layers}x{lcfg.vocab_size}"
        record.update({f"decode_{k}": v for k, v in
                       cost.utilization(per_tok_s).items()
                       if k in ("mfu", "hbm_util", "roofline_ms")})


def publish(records: dict) -> None:
    # shared merge+atomic writer: preserves config5's dict-valued
    # sub-records (published by measure_8b modes) and never leaves a
    # truncated BASELINE.json when a timeout kills the process mid-write
    from publish_util import merge_publish

    merge_publish(records)


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--configs", default=None,
                    help="comma-separated config numbers (default: all runnable)")
    ap.add_argument("--invokes", type=int, default=30)
    ap.add_argument("--no-publish", action="store_true")
    args = ap.parse_args()

    if args.configs:
        nums = [n if n in CONFIGS else int(n)
                for n in args.configs.split(",")]
    else:
        nums = [1, 2, "4t"]
        if tpu_reachable():
            nums += [3, 4, 5]
        else:
            print("device unreachable; measuring CPU configs only",
                  file=sys.stderr)
    records = {}
    d2h_floor = (measure_d2h_floor()
                 if any(CONFIGS[n]["platform"] == "device" for n in nums)
                 else None)
    failed = []
    for num in nums:
        print(f"config {num}: {CONFIGS[num]['recipe']} ...", file=sys.stderr)
        label = "config4_torch" if num == "4t" else f"config{num}"
        try:
            rec = measure_config(num, invokes=args.invokes,
                                 d2h_floor=d2h_floor)
        except Exception as e:  # one config must not discard the others
            failed.append(label)
            print(f"{label} FAILED: {e}", file=sys.stderr)
            continue
        records[label] = rec
        print(json.dumps({label: rec}))
    if records and not args.no_publish:
        publish(records)
        print(f"published -> {REPO / 'BASELINE.json'}", file=sys.stderr)
    return 1 if failed else 0


if __name__ == "__main__":
    raise SystemExit(main())
