"""SLO-aware admission control and request scheduling for the serve path.

The seed's ``BundleServer`` admitted every request behind a single
draining gate: under overload, latency grew without bound and nothing was
ever rejected explicitly. This package converts the batchers into a
*service* (the admission + scheduling layer of the vLLM/Orca lineage):

- :mod:`lambdipy_tpu.sched.queue` — a bounded queue with per-class FIFO
  lanes (interactive / batch / background);
- :mod:`lambdipy_tpu.sched.policy` — pluggable dequeue policies (fifo,
  priority, fair-share weighted round-robin);
- :mod:`lambdipy_tpu.sched.admission` — per-tenant token buckets,
  queue-depth caps and deadline-based shedding (429/503 + Retry-After);
- :mod:`lambdipy_tpu.sched.estimator` — an EWMA cost model of per-request
  service time (prefill + decode tokens) used for deadline feasibility.

:class:`Scheduler` below ties them together and is what
``runtime/server.py`` fronts every invoke with; the request-context
helpers let the batchers (``runtime/batching.py`` /
``runtime/continuous.py``) see the scheduling class of the request they
are serving without threading it through every handler signature.
"""

from __future__ import annotations

import os
import threading
import time
from dataclasses import dataclass, field

from lambdipy_tpu.runtime.metrics import LatencyStats
from lambdipy_tpu.sched.admission import AdmissionController, Shed
from lambdipy_tpu.sched.estimator import CostEstimator
from lambdipy_tpu.sched.policy import make_policy
from lambdipy_tpu.sched.queue import CLASSES, RequestQueue, Ticket

__all__ = ["Scheduler", "Shed", "Ticket", "CLASSES",
           "set_request_context", "clear_request_context",
           "current_request_class", "current_request_deadline_ms"]


# -- request context ---------------------------------------------------------
# The HTTP thread that admitted a request is the thread that runs the
# handler (and therefore enters the batchers). A thread-local carries the
# request's scheduling class down that call stack so batch formation can
# dequeue by policy without new parameters on every handler.

_ctx = threading.local()


def set_request_context(cls: str = "interactive", tenant: str = "anon",
                        deadline_ms: float | None = None) -> None:
    _ctx.cls, _ctx.tenant, _ctx.deadline_ms = cls, tenant, deadline_ms


def clear_request_context() -> None:
    _ctx.cls = _ctx.tenant = _ctx.deadline_ms = None


def current_request_class() -> str:
    return getattr(_ctx, "cls", None) or "interactive"


def current_request_deadline_ms() -> float | None:
    """The admitted request's ``x-deadline-ms``, if it carried one — the
    continuous engine uses it to cancel rows whose deadline expired
    mid-decode at the next drain barrier instead of decoding them to
    completion."""
    return getattr(_ctx, "deadline_ms", None)


# -- scheduler ---------------------------------------------------------------


@dataclass
class SchedConfig:
    """Operator surface, settable per bundle (``[payload.extra]``) or per
    serve process (CLI flags); every field has a serving-safe default."""

    policy: str = "fair"
    max_concurrency: int = 8       # invokes running at once
    queue_cap: int = 64            # queued (not yet running) requests
    rate: float = 0.0              # per-tenant tokens/s; 0 = unlimited
    burst: float = 0.0             # bucket size; 0 = 2 * rate
    default_cost_ms: float = 50.0  # estimator prior before any sample

    @classmethod
    def from_extra(cls, extra: dict | None, **overrides) -> "SchedConfig":
        """Bundle ``[payload.extra]`` keys (strings), then the
        LAMBDIPY_SCHED_POLICY env var (process-level operator intent,
        also read by the handler's batch formation), then explicit
        overrides (CLI/ctor, already typed). Unknown extra keys are
        ignored — extra is a shared namespace."""
        extra = extra or {}
        kw: dict = {}
        for name, cast in (("policy", str), ("max_concurrency", int),
                           ("queue_cap", int), ("rate", float),
                           ("burst", float), ("default_cost_ms", float)):
            raw = extra.get(f"sched_{name}")
            if raw is not None:
                kw[name] = cast(raw)
        env_policy = os.environ.get("LAMBDIPY_SCHED_POLICY")
        if env_policy:
            kw["policy"] = env_policy
        kw.update({k: v for k, v in overrides.items() if v is not None})
        return cls(**kw)


class Scheduler:
    """Admission + queue + slot handoff in front of the invoke path.

    A request thread calls :meth:`admit` (immediate accept-or-shed) and
    then :meth:`wait_turn` (parks in its class lane until the policy
    grants it one of ``max_concurrency`` run slots); :meth:`finish`
    releases the slot, wakes the next grant, and feeds the estimator.
    """

    def __init__(self, config: SchedConfig | None = None):
        self.config = config or SchedConfig()
        # normalize degenerate configs ONCE here so every consumer (the
        # admission depth check, wait math, the queue's own bound) sees
        # the same floors: queue_cap=0 would otherwise shed every
        # request 503 on an idle server
        self.config.max_concurrency = max(1, self.config.max_concurrency)
        self.config.queue_cap = max(1, self.config.queue_cap)
        self.policy = make_policy(self.config.policy)
        self.estimator = CostEstimator(
            default_ms=self.config.default_cost_ms)
        self.queue = RequestQueue(capacity=self.config.queue_cap)
        self.admission = AdmissionController(
            rate=self.config.rate, burst=self.config.burst)
        self._cond = threading.Condition()
        self._running = 0
        self.draining = False
        # observability: per-class queue-wait reservoirs + counters
        self.wait_stats = {c: LatencyStats(capacity=512) for c in CLASSES}
        self.admitted = 0
        self.completed = 0

    # -- admission -----------------------------------------------------------

    def admit(self, *, tenant: str = "anon", cls: str = "interactive",
              deadline_ms: float | None = None, prefill_tokens: int = 0,
              decode_tokens: int = 0) -> Ticket | Shed:
        if cls not in CLASSES:
            cls = "interactive"
        cost_ms = self.estimator.estimate(prefill_tokens, decode_tokens)
        with self._cond:
            ahead = self.queue.depth() + self._running
            # queue wait ≈ work ahead of us spread over the run slots
            wait_ms = (ahead * self.estimator.mean_ms()
                       / max(1, self.config.max_concurrency))
            shed = self.admission.check(
                tenant=tenant, cls=cls, deadline_ms=deadline_ms,
                queue_depth=self.queue.depth(),
                queue_cap=self.config.queue_cap,
                est_wait_ms=wait_ms, est_cost_ms=cost_ms,
                draining=self.draining)
            if shed is not None:
                return shed
            ticket = Ticket(cls=cls, tenant=tenant,
                            deadline_ms=deadline_ms, cost_ms=cost_ms,
                            prefill_tokens=prefill_tokens,
                            decode_tokens=decode_tokens)
            self.queue.push(ticket)
            self.admitted += 1
            self._pump_locked()
            return ticket

    # -- slot handoff ---------------------------------------------------------

    def _pump_locked(self) -> None:
        while self._running < self.config.max_concurrency:
            ticket = self.queue.pop(self.policy)
            if ticket is None:
                return
            now = time.monotonic()
            wait_ms = (now - ticket.enqueued) * 1e3
            # stamp the ticket so the server can echo queue_wait_ms in
            # the response body — a client (the autoscale bench) can
            # then window queue-wait client-side instead of reading the
            # replica's cumulative reservoir
            ticket.wait_ms = wait_ms
            self.wait_stats[ticket.cls].record(wait_ms)
            # deadline re-check at grant time: overload that built up
            # AFTER this request was admitted can make its deadline
            # unmeetable — shed it now instead of burning a device slot
            # on a response the client already abandoned
            if (ticket.deadline_ms is not None
                    and wait_ms + ticket.cost_ms > ticket.deadline_ms):
                ticket.expired = True
                ticket.granted = True  # wakes the waiter; it sends 503
                self.admission.count_shed("deadline", ticket.cls)
                self._cond.notify_all()
                continue
            ticket.granted = True
            self._running += 1
            self._cond.notify_all()

    def wait_turn(self, ticket: Ticket, timeout: float | None = None) -> bool:
        """Park until the policy grants this ticket a run slot. Returns
        False when the ticket expired (deadline shed at grant time) —
        the caller must NOT run the request and must not call finish."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cond:
            while not ticket.granted:
                remaining = (None if deadline is None
                             else deadline - time.monotonic())
                if remaining is not None and remaining <= 0:
                    self.queue.remove(ticket)
                    ticket.expired = True
                    self.admission.count_shed("deadline", ticket.cls)
                    return False
                self._cond.wait(timeout=remaining)
            return not ticket.expired

    def finish(self, ticket: Ticket, *, service_ms: float | None = None) -> None:
        with self._cond:
            self._running -= 1
            self.completed += 1
            if service_ms is not None:
                self.estimator.observe(service_ms, ticket.prefill_tokens,
                                       ticket.decode_tokens)
            self._pump_locked()
            self._cond.notify_all()

    # -- lifecycle / observability -------------------------------------------

    def drain(self) -> None:
        """Stop admitting; queued requests still run to completion."""
        with self._cond:
            self.draining = True

    def idle(self) -> bool:
        with self._cond:
            return self._running == 0 and self.queue.depth() == 0

    def report(self) -> dict:
        with self._cond:
            running = self._running
            depths = self.queue.snapshot()
            admitted, completed = self.admitted, self.completed
        waits = {}
        for c in CLASSES:
            rep = self.wait_stats[c].report()
            if rep["count"]:
                waits[c] = {"count": rep["count"],
                            "p50_ms": rep["p50_ms"],
                            "p99_ms": rep["p99_ms"]}
        return {
            "policy": self.policy.name,
            "max_concurrency": self.config.max_concurrency,
            "queue_cap": self.config.queue_cap,
            "running": running,
            "queued": depths,
            "admitted": admitted,
            "completed": completed,
            "shed": self.admission.shed_report(),
            "queue_wait": waits,
            "estimator": self.estimator.report(),
        }
