#!/bin/bash
# Probe-gated rerun of the remaining round-5 measurement modes: the
# tunnel wedged mid-suite (PROBE_LOG.jsonl 2026-07-31T06:13), so poll
# device health every 10 min and launch the remaining modes only when a
# full probe (enumerate + matmul + device_get) succeeds. Gives up when
# the deadline passes. The probe intentionally runs the WHOLE device
# path in a killable child: in the wedged state even backend init hangs
# indefinitely, and a probe that merely imports jax would hang the loop.
set -u
cd /root/repo
OUT=${OUT:-/tmp/r5m3}
mkdir -p "$OUT"
DEADLINE=$(( $(date +%s) + ${DEADLINE_HOURS:-7}*3600 ))

probe() {
  # -k 10: SIGKILL follows SIGTERM — a child stuck in an uninterruptible
  # device syscall (the wedge this script exists for) survives SIGTERM
  # and would otherwise hang the probe loop itself
  timeout -k 10 120 python -c "
import jax, jax.numpy as jnp
d = jax.devices()
assert d[0].platform == 'tpu', d
x = jnp.ones((256, 256), jnp.bfloat16)
v = float((x @ x).sum())
print('probe ok', v)
" >>"$OUT/probe.log" 2>&1
}

run() {
  local name=$1 to=$2
  shift 2
  echo "=== $name start $(date -u +%FT%TZ)" | tee -a "$OUT/driver.log"
  timeout "$to" "$@" >"$OUT/$name.json" 2>"$OUT/$name.err"
  echo "=== $name rc=$? end $(date -u +%FT%TZ)" | tee -a "$OUT/driver.log"
}

n=0
while [ "$(date +%s)" -lt "$DEADLINE" ]; do
  n=$((n + 1))
  if probe; then
    echo "=== probe $n ok $(date -u +%FT%TZ) — launching modes" \
      | tee -a "$OUT/driver.log"
    run kvquant 3000 python scripts/measure_8b.py --kv-quant --publish
    run prefill 3600 python scripts/measure_8b.py --prefill-table --publish
    run decode 2400 python scripts/measure_8b.py --publish
    run concurrent 2400 python scripts/measure_8b.py --concurrent --publish
    run coldstart 3600 python scripts/measure_8b.py --cold-start --publish
    echo "=== rerun suite done $(date -u +%FT%TZ)" | tee -a "$OUT/driver.log"
    exit 0
  fi
  echo "=== probe $n failed $(date -u +%FT%TZ)" | tee -a "$OUT/driver.log"
  sleep 600
done
echo "=== deadline passed, giving up $(date -u +%FT%TZ)" | tee -a "$OUT/driver.log"
