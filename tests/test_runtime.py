"""Serve-runtime integration: bundle boot, HTTP loop, deploy controller
(SURVEY.md §4 E — the rebuild's #1 new call stack; §6 failure rows)."""

import json
import urllib.error
import urllib.request
from pathlib import Path

import pytest

from lambdipy_tpu.buildengine import build_recipe
from lambdipy_tpu.bundle import assemble_bundle
from lambdipy_tpu.recipes.schema import load_recipe_dict


def make_model_bundle(tmp_path, *, model="llama-tiny", handler, extra=None,
                      mesh=None):
    """Build a tiny model bundle end-to-end (vendor nothing; base layer
    provides jax; payload params initialized at build time). Serving-
    program AOT snapshots default OFF here — every warmed boot would pay
    exports + round-trip compiles on the 1-core box; the feature has its
    own test (test_aot) and stays default-ON in production bundles. The
    automatic prefix cache defaults OFF for the same reason (every
    33+-token prompt would compile block/continuation programs on the
    1-core box); it has its own tests (test_prefixstore, which opt in)
    and stays default-ON in production bundles."""
    extra = dict(extra or ())
    extra.setdefault("serve_aot", "0")
    extra.setdefault("prefix_cache_mb", "0")
    # the background group-prefill warm daemon compiles burst programs
    # CONCURRENTLY with whatever test runs next — pure CPU steal on the
    # 1-core box; its wiring has its own opt-in test
    # (test_handler_daemon_warms_group_prefill)
    extra.setdefault("warm_group_prefill", "0")
    doc = {
        "schema": 1,
        "name": f"test-{model}",
        "version": "0.1",
        "device": "any",
        "base_layer": "jax-tpu",
        "requires": [],
        "payload": {
            "model": model,
            "handler": handler,
            "params": "init",
            "dtype": "float32",
            **({"mesh": mesh} if mesh else {}),
            **({"extra": extra} if extra else {}),
        },
    }
    recipe = load_recipe_dict(doc)
    result = build_recipe(recipe, tmp_path / "work", run_smoke=False)
    out = tmp_path / "bundle"
    assemble_bundle(result, out, with_payload=True)
    return out


@pytest.fixture(scope="module")
def llama_bundle(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("llama-bundle")
    return make_model_bundle(
        tmp, model="llama-tiny",
        handler="lambdipy_tpu.runtime.handlers:generate_handler",
        extra={"max_new_tokens": "4"})


def test_load_bundle_and_invoke(llama_bundle):
    from lambdipy_tpu.runtime.loader import load_bundle

    report = load_bundle(llama_bundle, warmup=True)
    assert report.warmup_result["ok"]
    assert {"manifest", "syspath", "compile_cache", "handler_import",
            "init", "warmup"} <= set(report.stages)
    out = report.handler.invoke(report.state, {"tokens": [1, 2, 3]})
    assert out["ok"] and out["n_new"] == 4
    assert (llama_bundle / "compile_cache").is_dir()


def test_resnet_bundle_image_handler(tmp_path):
    from lambdipy_tpu.runtime.loader import load_bundle

    bundle = make_model_bundle(
        tmp_path, model="resnet50-tiny",
        handler="lambdipy_tpu.runtime.handlers:image_classify_handler")
    report = load_bundle(bundle)
    out = report.handler.invoke(report.state, {"random": True})
    assert out["ok"] and len(out["top5"][0]) == 5


def test_hello_bundle_without_params(tmp_path):
    from lambdipy_tpu.runtime.loader import load_bundle

    bundle = make_model_bundle(
        tmp_path, model="hello",
        handler="lambdipy_tpu.runtime.handlers:hello_handler")
    report = load_bundle(bundle)
    out = report.handler.invoke(report.state, {"n": 16, "seed": 7})
    assert out["ok"] and isinstance(out["logdet"], float)


def _get(url):
    with urllib.request.urlopen(url, timeout=30) as r:
        return json.loads(r.read())


def _post(url, payload):
    req = urllib.request.Request(url, data=json.dumps(payload).encode(),
                                 headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=60) as r:
        return json.loads(r.read())


def test_http_server_full_loop(llama_bundle):
    from lambdipy_tpu.runtime.server import BundleServer

    server = BundleServer(llama_bundle, port=0).start_background()
    base = f"http://127.0.0.1:{server.port}"
    try:
        health = _get(f"{base}/healthz")
        assert health["ok"] and "init" in health["cold_start"]
        out = _post(f"{base}/invoke", {"tokens": [1, 2, 3], "max_new_tokens": 2})
        assert out["ok"] and out["n_new"] == 2
        metrics = _get(f"{base}/metrics")
        assert metrics["count"] >= 1 and metrics["p50_ms"] > 0
        # the decode server's live counters surface through /metrics
        assert metrics["handler"]["compile_count"] >= 1
        assert metrics["handler"]["decode_buckets"]
        # failure detection: bad payload shape -> 500, counted, server alive
        with pytest.raises(urllib.error.HTTPError) as e:
            _post(f"{base}/invoke", {"tokens": "not-a-list"})
        assert e.value.code == 500
        assert _get(f"{base}/metrics")["errors"] >= 1
        with pytest.raises(urllib.error.HTTPError) as e:
            _get(f"{base}/nope")
        assert e.value.code == 404
        assert _get(f"{base}/healthz")["ok"]  # still alive
    finally:
        server.stop()


@pytest.mark.slow
def test_local_deploy_subprocess_lifecycle(llama_bundle, tmp_path):
    """Full deploy path: subprocess server (CPU via LAMBDIPY_PLATFORM),
    readiness, invoke over HTTP, watchdog health, drain + stop."""
    from lambdipy_tpu.runtime.deploy import DeployError, LocalRuntime

    rt = LocalRuntime(tmp_path / "deployments.json")
    dep = rt.deploy("t1", llama_bundle, env={
        "LAMBDIPY_PLATFORM": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
    })
    try:
        assert rt.health("t1")["ok"]
        out = rt.invoke("t1", {"tokens": [1, 2], "max_new_tokens": 2})
        assert out["ok"]
        with pytest.raises(DeployError, match="already exists"):
            rt.deploy("t1", llama_bundle)
        assert [d.name for d in rt.list()] == ["t1"]
    finally:
        rt.stop("t1")
    assert rt.list() == []


@pytest.mark.slow
def test_warm_populates_compile_cache_and_speeds_boot(tmp_path):
    """SURVEY.md §9.6: the bundle ships a warm XLA compile cache; a second
    boot's warmup must hit it (no recompile)."""
    import os
    import subprocess
    import sys as _sys

    bundle = make_model_bundle(
        tmp_path, model="resnet50-tiny",
        handler="lambdipy_tpu.runtime.handlers:image_classify_handler")
    env = dict(os.environ)
    env["LAMBDIPY_PLATFORM"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    repo_root = str(Path(__file__).resolve().parents[1])
    env["PYTHONPATH"] = os.pathsep.join(
        [repo_root] + [p for p in env.get("PYTHONPATH", "").split(os.pathsep) if p])
    r1 = subprocess.run(
        [_sys.executable, "-m", "lambdipy_tpu.runtime.warm", str(bundle)],
        capture_output=True, text=True, env=env, timeout=600)
    assert r1.returncode == 0, r1.stderr
    out1 = json.loads(r1.stdout.strip().splitlines()[-1])
    assert out1["cache_entries"] > 0
    # second warm run: compile stage should hit the shipped cache
    r2 = subprocess.run(
        [_sys.executable, "-m", "lambdipy_tpu.runtime.warm", str(bundle)],
        capture_output=True, text=True, env=env, timeout=600)
    out2 = json.loads(r2.stdout.strip().splitlines()[-1])
    assert out2["stages"]["warmup"] + out2["stages"]["init"] < \
        out1["stages"]["warmup"] + out1["stages"]["init"]


def test_profile_endpoint_captures_trace(llama_bundle):
    from lambdipy_tpu.runtime.server import BundleServer

    server = BundleServer(llama_bundle, port=0).start_background()
    try:
        out = _post(f"http://127.0.0.1:{server.port}/profile", {"invokes": 1})
        assert out["ok"]
        assert Path(out["dir"]).is_dir()
    finally:
        server.stop()


@pytest.mark.slow
def test_watchdog_restarts_killed_server(llama_bundle, tmp_path):
    """Fault injection (SURVEY.md §6): SIGKILL the serving process mid-life;
    the supervisor must respawn it on the same port and invokes recover."""
    import os
    import signal
    import time

    from lambdipy_tpu.runtime.deploy import LocalRuntime

    rt = LocalRuntime(tmp_path / "deployments.json")
    dep = rt.deploy("wd", llama_bundle, env={
        "LAMBDIPY_PLATFORM": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
    })
    try:
        first = rt.health("wd")
        assert first["ok"] and not first["draining"]
        server_pid = first["pid"]
        assert server_pid != dep.pid  # supervisor fronts a distinct worker
        os.kill(server_pid, signal.SIGKILL)  # crash the worker, not the sup
        deadline = time.monotonic() + 120
        second = None
        while time.monotonic() < deadline:
            try:
                second = rt.health("wd")
                if second["pid"] != server_pid:
                    break
            except Exception:
                pass
            time.sleep(0.5)
        assert second is not None and second["pid"] != server_pid, \
            "server was not respawned"
        out = rt.invoke("wd", {"tokens": [1, 2], "max_new_tokens": 2})
        assert out["ok"]
    finally:
        rt.stop("wd")
    assert rt.list() == []


def test_supervisor_gives_up_after_max_restarts(tmp_path):
    """A bundle that can never boot must not restart-loop forever."""
    import os
    import subprocess
    import sys as _sys

    env = dict(os.environ)
    env["LAMBDIPY_MAX_RESTARTS"] = "1"
    repo_root = str(Path(__file__).resolve().parents[1])
    env["PYTHONPATH"] = os.pathsep.join(
        [repo_root] + [p for p in env.get("PYTHONPATH", "").split(os.pathsep) if p])
    r = subprocess.run(
        [_sys.executable, "-m", "lambdipy_tpu.runtime.supervisor",
         str(tmp_path / "not-a-bundle")],
        capture_output=True, text=True, env=env, timeout=120)
    assert r.returncode == 1
    assert "giving up" in r.stderr


def test_server_drain_rejects_new_invokes(llama_bundle):
    import threading
    import urllib.error

    from lambdipy_tpu.runtime.server import BundleServer

    server = BundleServer(llama_bundle, port=0).start_background()
    base = f"http://127.0.0.1:{server.port}"
    try:
        assert _post(f"{base}/invoke", {"tokens": [1], "max_new_tokens": 1})["ok"]
        server.draining = True
        assert _get(f"{base}/healthz")["draining"]
        with pytest.raises(urllib.error.HTTPError) as e:
            _post(f"{base}/invoke", {"tokens": [1]})
        assert e.value.code == 503
    finally:
        server.draining = False
        threading.Thread(target=server.stop, daemon=True).start()


def test_generate_handler_null_knobs(llama_bundle):
    """JSON null for every sampling knob (incl. max_new_tokens) means 'use
    the default' — it must not 500 (VERDICT r2 weak #7)."""
    from lambdipy_tpu.runtime.loader import load_bundle

    report = load_bundle(llama_bundle)
    out = report.handler.invoke(report.state, {
        "tokens": [1, 2, 3], "max_new_tokens": None, "temperature": None,
        "top_k": None, "top_p": None, "seed": None, "eos_id": None})
    assert out["ok"] and out["n_new"] == 4  # bundle default_new


def test_background_bucket_warm(tmp_path):
    """warm_buckets pre-compiles the listed prompt buckets on a daemon
    thread after init: once done, a first request in that bucket triggers
    zero new compiles, and progress is visible through stats()."""
    import time as _time

    from lambdipy_tpu.runtime.loader import load_bundle

    bundle = make_model_bundle(
        tmp_path, model="llama-tiny",
        handler="lambdipy_tpu.runtime.handlers:generate_handler",
        # the automatic prefix cache would route the 50-token probe into
        # continuation programs instead of the warmed fused bucket; this
        # test exercises the bucket-warm machinery, so keep it off
        extra={"max_new_tokens": "4", "warm_buckets": "64",
               "prefix_cache_mb": "0"})
    report = load_bundle(bundle, warmup=False)
    # the warm thread starts only after the FIRST invoke completes (so it
    # can never contend with the boot warmup); trigger it
    assert report.state.stats().get("warm_buckets", {}).get("done") in ([], None)
    assert report.handler.invoke(report.state, {"tokens": [1, 2]})["ok"]
    deadline = _time.monotonic() + 120
    while _time.monotonic() < deadline:
        wb = report.state.stats().get("warm_buckets", {})
        assert not wb.get("errors"), wb
        if wb.get("done") == [64]:
            break
        _time.sleep(0.5)
    else:
        raise AssertionError(f"bucket warm never finished: {report.state.stats()}")
    count = report.state.stats()["compile_count"]
    out = report.handler.invoke(report.state, {
        "tokens": list(range(1, 51)), "max_new_tokens": 4})  # 50 -> bucket 64
    assert out["ok"]
    assert report.state.stats()["compile_count"] == count  # warm hit


def test_openai_completions_endpoint(llama_bundle):
    """/v1/completions serves OpenAI-shaped requests over the generate
    handler: token-array prompts work without a tokenizer, greedy matches
    /invoke, eos sets finish_reason, bad requests get OpenAI-style
    errors, and stream=true emits SSE events closed by [DONE]."""
    import json as _json
    import threading
    import urllib.error
    import urllib.request

    from lambdipy_tpu.runtime.server import BundleServer

    server = BundleServer(llama_bundle, warmup=False).start_background()
    base = f"http://127.0.0.1:{server.port}"

    def post(path, payload, timeout=60):
        req = urllib.request.Request(
            f"{base}{path}", data=_json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"}, method="POST")
        return urllib.request.urlopen(req, timeout=timeout)

    try:
        plain = _post(f"{base}/invoke",
                      {"tokens": [1, 2, 3], "max_new_tokens": 6})
        with post("/v1/completions", {"prompt": [1, 2, 3], "max_tokens": 6,
                                      "temperature": 0}) as resp:
            body = _json.loads(resp.read())
        assert body["object"] == "text_completion"
        choice = body["choices"][0]
        assert choice["tokens"] == plain["tokens"][0]
        assert choice["finish_reason"] == "length"
        assert body["usage"] == {"prompt_tokens": 3, "completion_tokens": 6,
                                 "total_tokens": 9}
        # eos latching -> finish_reason stop
        eos = plain["tokens"][0][1]
        with post("/v1/completions", {"prompt": [1, 2, 3], "max_tokens": 6,
                                      "temperature": 0, "eos_id": eos}) as resp:
            body = _json.loads(resp.read())
        assert body["choices"][0]["finish_reason"] == "stop"
        # string prompt without a tokenizer -> 400 with OpenAI error shape
        try:
            post("/v1/completions", {"prompt": "hello", "max_tokens": 4})
            raise AssertionError("expected 400")
        except urllib.error.HTTPError as e:
            assert e.code == 400
            assert "error" in _json.loads(e.read())
        # SSE streaming
        with post("/v1/completions", {"prompt": [1, 2, 3], "max_tokens": 6,
                                      "temperature": 0, "stream": True,
                                      "segment": 4}) as resp:
            assert resp.headers["Content-Type"] == "text/event-stream"
            events = [ln.decode().strip()[len("data: "):]
                      for ln in resp if ln.strip().startswith(b"data: ")]
        assert events[-1] == "[DONE]"
        toks = [t for e in events[:-1]
                for t in _json.loads(e)["choices"][0]["tokens"]]
        assert toks == plain["tokens"][0]
        # streamed logprobs ride each SSE chunk
        with post("/v1/completions", {"prompt": [1, 2, 3], "max_tokens": 6,
                                      "temperature": 0, "stream": True,
                                      "segment": 4, "logprobs": 1}) as resp:
            evs = [_json.loads(ln.decode().strip()[6:])
                   for ln in resp if ln.strip().startswith(b"data: ")
                   and not ln.strip().endswith(b"[DONE]")]
        tok_evs = [e for e in evs if e["choices"][0]["tokens"]]
        assert tok_evs, evs
        for e in tok_evs:
            ch = e["choices"][0]
            assert len(ch["logprobs"]["token_logprobs"]) == len(ch["tokens"])
        # logprobs: per-token model logprobs in OpenAI shape
        with post("/v1/completions", {"prompt": [1, 2, 3], "max_tokens": 4,
                                      "temperature": 0,
                                      "logprobs": 1}) as resp:
            body = _json.loads(resp.read())
        lp = body["choices"][0]["logprobs"]
        assert len(lp["token_logprobs"]) == len(body["choices"][0]["tokens"])
        assert all(x <= 1e-6 for x in lp["token_logprobs"])
        try:
            post("/v1/completions", {"prompt": [1], "logprobs": 5})
            raise AssertionError("expected 400")
        except urllib.error.HTTPError as e:
            assert e.code == 400
        # the shim shares /invoke's drain bracket: no new work while draining
        server.draining = True
        try:
            post("/v1/completions", {"prompt": [1], "max_tokens": 1})
            raise AssertionError("expected 503")
        except urllib.error.HTTPError as e:
            assert e.code == 503
        finally:
            server.draining = False
    finally:
        threading.Thread(target=server.stop, daemon=True).start()


def test_http_streaming_invoke(llama_bundle):
    """`stream: true` returns chunked ndjson whose concatenated tokens
    equal the non-streamed response; non-stream requests still work on
    the same server."""
    import json as _json
    import threading
    import urllib.request

    from lambdipy_tpu.runtime.server import BundleServer

    server = BundleServer(llama_bundle, warmup=False).start_background()
    base = f"http://127.0.0.1:{server.port}"
    try:
        plain = _post(f"{base}/invoke",
                      {"tokens": [1, 2, 3], "max_new_tokens": 8})
        req = urllib.request.Request(
            f"{base}/invoke",
            data=_json.dumps({"tokens": [1, 2, 3], "max_new_tokens": 8,
                              "stream": True, "segment": 3}).encode(),
            headers={"Content-Type": "application/json"}, method="POST")
        with urllib.request.urlopen(req, timeout=60) as resp:
            assert resp.headers.get("Content-Type") == "application/x-ndjson"
            lines = [_json.loads(ln) for ln in resp if ln.strip()]
        assert lines[-1].get("done") and lines[-1]["n_new"] == 8
        toks = []
        for ln in lines[:-1]:
            assert ln["ok"], ln
            toks.extend(ln["tokens"][0])
        assert toks == plain["tokens"][0]
    finally:
        threading.Thread(target=server.stop, daemon=True).start()


def test_generate_handler_ragged_json_rows(llama_bundle):
    """A JSON list of different-length prompt rows decodes as one ragged
    batch (each row from its own prompt end) and matches solo serving;
    equal-length rows still take the rectangular path."""
    import numpy as np

    from lambdipy_tpu.runtime.loader import load_bundle

    report = load_bundle(llama_bundle)
    ragged = report.handler.invoke(report.state, {
        "tokens": [[1, 2, 3], [4, 5, 6, 7, 8]], "max_new_tokens": 4})
    assert ragged["ok"] and len(ragged["tokens"]) == 2, ragged
    for row in ragged["tokens"]:
        assert len(row) == 4
    solo = report.handler.invoke(report.state, {
        "tokens": [4, 5, 6, 7, 8], "max_new_tokens": 4})
    assert ragged["tokens"][1] == solo["tokens"][0]
    rect = report.handler.invoke(report.state, {
        "tokens": [[1, 2, 3], [4, 5, 6]], "max_new_tokens": 4})
    assert rect["ok"] and np.asarray(rect["tokens"]).shape == (2, 4)
    empty = report.handler.invoke(report.state,
                                  {"tokens": [[1, 2], []]})
    assert not empty["ok"] and "empty" in empty["error"]


def test_generate_handler_prefix_caching(llama_bundle):
    """`prefix` requests reuse the cached prefix KV and match the
    concatenated-prompt response; streamed prefix requests consume the
    cached KV too (prefix_cached true) with identical tokens."""
    import numpy as np

    from lambdipy_tpu.runtime.loader import load_bundle

    report = load_bundle(llama_bundle)
    prefix, suffix = [1, 2, 3, 4, 5, 6, 7], [9, 8]
    full = report.handler.invoke(report.state,
                                 {"tokens": prefix + suffix,
                                  "max_new_tokens": 6})
    via = report.handler.invoke(report.state,
                                {"prefix": prefix, "tokens": suffix,
                                 "max_new_tokens": 6})
    assert via["ok"] and via["prefix_cached"], via
    assert via["tokens"] == full["tokens"]
    chunks = list(report.state.invoke_stream(
        {"prefix": prefix, "tokens": suffix, "max_new_tokens": 6}))
    streamed = [t for c in chunks if c.get("ok") and "tokens" in c
                for t in c["tokens"][0]]
    assert streamed == full["tokens"][0]
    summary = chunks[-1]
    assert summary.get("done") and summary["prefix_cached"] is True, summary
    assert summary["n_prompt"] == len(prefix) + len(suffix)
    bad = report.handler.invoke(report.state,
                                {"prefix": [], "tokens": suffix})
    assert not bad["ok"]


def test_generate_handler_serves_compile_once(llama_bundle):
    """The handler routes through LlamaServer: varied lengths and knobs in
    one bucket reuse a single compiled program."""
    from lambdipy_tpu.runtime.loader import load_bundle

    report = load_bundle(llama_bundle)
    r1 = report.handler.invoke(report.state, {"tokens": [1, 2, 3]})
    r2 = report.handler.invoke(report.state, {
        "tokens": [4, 5, 6, 7, 8], "temperature": 0.9, "top_k": 3,
        "seed": 5})
    assert r1["ok"] and r2["ok"]


def test_bundle_params_from_checkpoint_path(tmp_path):
    """payload.params may be a checkpoint PATH (the schema's third form —
    real deployments ship pre-built weights instead of build-time init):
    a params dir or a bare .fpk is linked/copied into the bundle and the
    served weights are EXACTLY the provided ones, not a fresh init."""
    import numpy as np

    from lambdipy_tpu.bundle.flatpack import save_checkpoint_files
    from lambdipy_tpu.models import registry
    from lambdipy_tpu.recipes.schema import load_recipe_dict
    from lambdipy_tpu.buildengine import build_recipe
    from lambdipy_tpu.bundle import assemble_bundle
    from lambdipy_tpu.runtime.loader import load_bundle

    # distinctive weights: seed 7, not the handler default of 0
    adapter = registry.get("llama-tiny").build()
    params = adapter.init_params(seed=7)
    src_dir = tmp_path / "ckpt"
    save_checkpoint_files(src_dir, params, "fpk")

    for src in (src_dir, src_dir / "params.fpk"):  # dir AND bare-file form
        doc = {
            "schema": 1, "name": "test-path-params", "version": "0.1",
            "device": "any", "base_layer": "jax-tpu", "requires": [],
            "payload": {
                "model": "llama-tiny",
                "handler": "lambdipy_tpu.runtime.handlers:generate_handler",
                "params": str(src), "dtype": "float32",
                "extra": {"max_new_tokens": "4"},
            },
        }
        work = tmp_path / f"w-{src.name}"
        result = build_recipe(load_recipe_dict(doc), work, run_smoke=False)
        bundle = work / "bundle"
        manifest = assemble_bundle(result, bundle, with_payload=True)
        assert manifest["payload"]["params_info"]["format"] == "external"
        report = load_bundle(bundle, warmup=False)
        out = report.handler.invoke(report.state,
                                    {"tokens": [1, 2, 3], "max_new_tokens": 4})
        assert out["ok"], out
        import jax.numpy as jnp

        expected = adapter.generate(params, jnp.asarray([[1, 2, 3]],
                                                        jnp.int32),
                                    max_new_tokens=4)
        np.testing.assert_array_equal(np.asarray(out["tokens"]),
                                      np.asarray(expected))

    import pytest as _pytest
    doc["payload"]["params"] = str(tmp_path / "nope")
    with _pytest.raises(Exception, match="neither"):
        result = build_recipe(load_recipe_dict(doc), tmp_path / "w-bad",
                              run_smoke=False)
        assemble_bundle(result, tmp_path / "w-bad" / "bundle",
                        with_payload=True)


def test_min_bucket_recipe_knob_reaches_server(tmp_path):
    """[payload.extra] min_bucket = 1 must reach LlamaServer: a
    max_new_tokens=1 invoke then runs a ONE-step decode scan instead of
    the default 16-step bucket (~16 wasted weight reads at 8B for
    scoring workloads)."""
    from lambdipy_tpu.runtime.loader import load_bundle

    bundle = make_model_bundle(
        tmp_path, model="llama-tiny",
        handler="lambdipy_tpu.runtime.handlers:generate_handler",
        extra={"max_new_tokens": "4", "min_bucket": "1"})
    r = load_bundle(bundle, warmup=True)
    out = r.handler.invoke(r.state, {"tokens": [1, 2, 3],
                                     "max_new_tokens": 1})
    assert out["ok"] and len(out["tokens"][0]) == 1
    buckets = r.state.stats()["decode_buckets"]
    assert any(b[-1] == 1 for b in buckets), buckets
