"""Measure the REAL Llama-3-8B dims on the chip (VERDICT r3 missing #1).

Every published decode number so far was the 768x6x16384 micro exemplar;
this script builds the actual 4096x32x128256 int8 model — ~7.5 GB of
matmul weights, which fit a single v5e-1's 16 GB HBM with room for a
1k-context KV cache — and measures, through the same LlamaServer serving
machinery the bundle handler uses:

- batch-1 and batch-8 decode tok/s, net of the transport's per-fetch RTT
  (the environment's remote tunnel; ~0 on attached hardware), with
  roofline/HBM-utilization accounting (utils/roofline.py);
- prefill latency at a 512-token prompt;
- the cold-start decomposition at 8B scale: flatpack load, host->device
  weight transfer, and first-program compile.

Params are random-init int8 — FLOPs and HBM bytes do not care what the
weights are — generated ONCE into the framework cache as a flatpack file
(~8 GB, ~2 min) and reused by later runs and by bench.py's decode8b
stage. The pytree layout is derived with jax.eval_shape from the same
init the bundle path uses, so the file loads exactly like a real
checkpoint.

Usage: python scripts/measure_8b.py [--batch 1,8] [--n-new 64]
       [--publish]   # writes BASELINE.json published.config5
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO))

from bench import _timed  # noqa: E402 — shared timing/RTT methodology

# the exemplar-scale knobs shared with recipes/builtin/jax-llama3-8b.toml:
# real model dims, context capped so prompt+decode KV fits comfortably
# beside 8 GB of weights on one chip
DIMS = dict(vocab_size=128256, hidden=4096, layers=32, heads=32,
            kv_heads=8, mlp=14336, max_len=1024)


def params_path() -> Path:
    cache = Path(os.environ.get("LAMBDIPY_CACHE_DIR",
                                os.path.expanduser("~/.lambdipy-tpu/cache")))
    return cache / "llama3-8b-int8-random.fpk"


def ensure_params(path: Path) -> float:
    """Generate the random-init int8 8B flatpack once; returns seconds
    spent (0.0 when the cached file already exists)."""
    if path.is_file():
        return 0.0
    import jax
    import numpy as np
    import ml_dtypes

    from lambdipy_tpu.bundle import flatpack
    from lambdipy_tpu.models import registry

    t0 = time.monotonic()
    adapter = registry.get("llama3-8b").build(
        dtype="bfloat16", quant="int8", extra=dict(DIMS))
    shapes = jax.eval_shape(lambda: adapter.init_params(seed=0))
    rng = np.random.default_rng(0)

    def fill(leaf):
        if leaf.dtype == np.int8:  # quantized kernels (the 7.5 GB)
            return rng.integers(-127, 128, leaf.shape, dtype=np.int8)
        if leaf.dtype == ml_dtypes.bfloat16:  # embedding table
            return (rng.standard_normal(leaf.shape, np.float32) * 0.02
                    ).astype(ml_dtypes.bfloat16)
        if np.issubdtype(leaf.dtype, np.floating):
            if leaf.ndim == 2:  # QDense per-channel scales [1, out]:
                # uniform int8 * this scale ~ lecun-magnitude weights, so
                # bf16 activations stay finite through 32 layers
                return np.full(
                    leaf.shape, 1.0 / (127.0 * DIMS["hidden"] ** 0.5),
                    np.float32)
            return np.ones(leaf.shape, np.float32)  # RMSNorm scales
        raise ValueError(f"unhandled dtype {leaf.dtype}")

    tree = jax.tree.map(fill, shapes)
    path.parent.mkdir(parents=True, exist_ok=True)
    flatpack.save(path, tree)
    return time.monotonic() - t0


def measure(batches=(1, 8), n_new: int = 64, prompt_len: int = 8,
            prefill_len: int = 512, do_prefill: bool = True) -> dict:
    import jax
    import jax.numpy as jnp

    from lambdipy_tpu.bundle import flatpack
    from lambdipy_tpu.models import registry
    from lambdipy_tpu.models.llama import LlamaConfig
    from lambdipy_tpu.utils import roofline

    record: dict = {"dims": f"{DIMS['hidden']}x{DIMS['layers']}"
                            f"x{DIMS['vocab_size']}",
                    "quant": "int8", "n_new": n_new,
                    "measured_at": time.strftime("%Y-%m-%d")}
    gen_s = ensure_params(params_path())
    if gen_s:
        record["param_gen_s"] = round(gen_s, 1)

    devices = jax.devices()
    record["platform"] = devices[0].platform
    t0 = time.monotonic()
    # bulk grouped upload + device-side unpack (flatpack.device_load):
    # measured 54.6 s for the 8.5 GB tree vs 252 s for per-leaf
    # device_put through this transport
    params = flatpack.device_load(params_path())
    # transfers are async (and block_until_ready returns at submission on
    # this transport): a scalar reduction fetched host-side observes the
    # upload actually complete
    for leaf in jax.tree.leaves(params)[-1:]:
        float(jnp.asarray(leaf).astype(jnp.float32).sum())
    record["weight_upload_s"] = round(time.monotonic() - t0, 2)
    record["weight_bytes"] = int(roofline.param_bytes(params))

    cfg = LlamaConfig(**DIMS, quant="int8", dtype=jnp.bfloat16)
    adapter = registry.get("llama3-8b").build(
        dtype="bfloat16", quant="int8", extra=dict(DIMS))
    server = adapter.make_server(params)

    # transport floor: every fresh device->host fetch pays one RTT here
    # (single source of the methodology: bench.py)
    from bench import _measure_rtt_ms

    rtt = _measure_rtt_ms(jax, jnp)
    record["d2h_rtt_ms"] = round(rtt, 2)

    prompt = list(range(1, prompt_len + 1))
    for b in batches:
        rows = [prompt] * b
        t0 = time.monotonic()
        server.generate(rows, max_new_tokens=n_new)  # compile + warm
        key = f"b{b}"
        record[f"{key}_first_call_s"] = round(time.monotonic() - t0, 1)
        times = [_timed(lambda: server.generate(rows, max_new_tokens=n_new))
                 for _ in range(5)]
        net_ms = max(0.1, statistics.median(times) - rtt)
        tok_s = b * n_new / (net_ms / 1e3)
        cost = roofline.llama_decode_step_cost(
            cfg, batch=b, cache_len=prompt_len + n_new // 2)
        util = cost.utilization(net_ms / n_new / 1e3)
        bound = roofline.llama_decode_tok_s_bound(
            cfg, batch=b, cache_len=prompt_len + n_new // 2)
        record.update({
            f"{key}_decode_tok_s": round(tok_s, 1),
            f"{key}_decode_net_ms": round(net_ms, 1),
            f"{key}_decode_hbm_util": util["hbm_util"],
            f"{key}_decode_mfu": util["mfu"],
            f"{key}_roofline_tok_s": round(bound, 1),
        })
        print(json.dumps({k: v for k, v in record.items()
                          if k.startswith(key)}), file=sys.stderr)

    if not do_prefill:
        return record
    # prefill: long-prompt first-token latency (compute-bound regime).
    # A max_new_tokens=1 call still runs a bucketed decode scan after
    # the prefill (min_bucket steps = ~16 weight reads = ~180 ms at
    # 8B); drop the server to a ONE-step scan and subtract that step's
    # cost (the already-measured b1 per-step decode time) so the
    # published number is the prefill itself.
    server.min_bucket = 1
    long_prompt = list(range(1, prefill_len + 1))
    t0 = time.monotonic()
    server.generate(long_prompt, max_new_tokens=1)  # compile
    record["prefill_compile_s"] = round(time.monotonic() - t0, 1)
    times = [_timed(lambda: server.generate(long_prompt, max_new_tokens=1))
             for _ in range(5)]
    # b1-derived step cost slightly overcounts (it amortizes the tiny
    # prompt prefill into the divisor, ~1.6% at n_new=64); a run that
    # skipped b1 publishes uncorrected and SAYS so
    record["prefill_step_corrected"] = "b1_decode_net_ms" in record
    step_ms = (record["b1_decode_net_ms"] / n_new
               if record["prefill_step_corrected"] else 0.0)
    net_ms = max(0.1, statistics.median(times) - rtt - step_ms)
    pcost = roofline.llama_prefill_cost(cfg, batch=1, seq_len=prefill_len)
    record["prefill_512_net_ms"] = round(net_ms, 1)
    record["prefill_512_mfu"] = pcost.utilization(net_ms / 1e3)["mfu"]
    return record


RECIPE_TMPL = """\
# generated by scripts/measure_8b.py --cold-start: the real 8B dims at
# tp=1 with pre-built weights (payload.params = checkpoint path), so the
# measured cold start is weights-load + boot, not build-time init
schema = 1
name = "jax-llama3-8b-local"
version = "1.0.0"
description = "Llama-3-8B int8 single-chip bundle from pre-built weights"
python = ["3.12"]
device = "tpu-v5e-1"
base_layer = "jax-tpu"
requires = []

[payload]
model = "llama3-8b"
handler = "lambdipy_tpu.runtime.handlers:generate_handler"
params = "{params}"
dtype = "bfloat16"
quant = "int8"
batch_size = 1

[payload.extra]
vocab_size = {vocab_size}
hidden = {hidden}
layers = {layers}
heads = {heads}
kv_heads = {kv_heads}
mlp = {mlp}
max_len = {max_len}
max_new_tokens = 32
# match the production recipe defaults (VERDICT r5 #6) so the measured
# cold start covers the engine's programs too
batch_mode = "continuous"
batch_max = 8
"""


def measure_cold_start(n_invokes: int = 5) -> dict:
    """The 8B cold start through the REAL path: build a bundle from the
    pre-built fpk (hardlinked), deploy it (subprocess server + readiness),
    and time build / boot stages / first invokes. On this image the boot
    is dominated by pushing ~8 GB of weights through a ~50 MB/s tunnel —
    the decomposition (from /healthz) separates that transport cost from
    the framework's own work."""
    import statistics
    import subprocess
    import tempfile

    from lambdipy_tpu.runtime.deploy import LocalRuntime

    record: dict = {"dims": f"{DIMS['hidden']}x{DIMS['layers']}"
                            f"x{DIMS['vocab_size']}",
                    "measured_at": time.strftime("%Y-%m-%d")}
    gen_s = ensure_params(params_path())
    if gen_s:
        record["param_gen_s"] = round(gen_s, 1)
    work = Path(tempfile.mkdtemp(prefix="coldstart-8b-"))
    rdir = work / "recipes"
    rdir.mkdir()
    (rdir / "jax-llama3-8b-local.toml").write_text(
        RECIPE_TMPL.format(params=params_path(), **DIMS))
    bundle = work / "bundle"
    t0 = time.monotonic()
    proc = subprocess.run(
        [sys.executable, "-m", "lambdipy_tpu", "build",
         "jax-llama3-8b-local", "--recipe-dir", str(rdir),
         "--out", str(bundle)],
        capture_output=True, text=True, cwd=str(REPO), timeout=1800)
    if proc.returncode != 0:
        raise RuntimeError(f"build failed: {proc.stderr[-800:]}")
    record["build_s"] = round(time.monotonic() - t0, 1)

    rt = LocalRuntime(work / "deployments.json")
    t0 = time.monotonic()
    rt.deploy("c8b", bundle, ready_timeout=1800.0)
    record["deploy_wall_s"] = round(time.monotonic() - t0, 1)
    try:
        health = rt.health("c8b")
        cs = health["cold_start"]
        record["cold_start_s"] = round(cs.get("total", 0.0), 1)
        record["cold_start_stages"] = {k: round(v, 2)
                                       for k, v in cs.items()}
        # overlap diagnostics (VERDICT r5 #5): how many serving programs
        # the boot deserialized CONCURRENTLY with the weight upload, how
        # long that preload ran, and the AOT hit count — distinguishes
        # "overlap engaged and hid program loads" from "aot/ was empty
        # and warmup paid fresh remote compiles"
        try:
            h = rt.metrics("c8b").get("handler", {})
            record["aot_preload"] = h.get("aot_preload")
            record["aot_hits"] = h.get("aot_hits")
            record["warmup_compile_count"] = h.get("compile_count")
        except Exception as e:  # diagnostics must not fail the mode
            record["aot_preload"] = f"unavailable: {e}"
        times = []
        for _ in range(n_invokes):
            t = time.monotonic()
            out = rt.invoke("c8b", {"tokens": [[1, 2, 3, 4, 5, 6, 7, 8]],
                                    "max_new_tokens": 32}, timeout=300.0)
            assert out.get("ok"), out
            times.append((time.monotonic() - t) * 1e3)
        record["invoke_p50_ms"] = round(statistics.median(times), 1)
        record["invoke_decode_tok_s"] = round(
            32 / (statistics.median(times) / 1e3), 1)
    finally:
        rt.stop("c8b")
    # the bundle can hold a full COPY of the ~8.5 GB fpk (the hardlink
    # falls back to copy across filesystems); leaving it per run would
    # exhaust /tmp. Reached only on success, so failure keeps the serve
    # log for diagnosis.
    import shutil

    shutil.rmtree(work, ignore_errors=True)
    return record


def measure_speculative(n_new: int = 64, k: int = 8) -> dict:
    """Speculative decode at 8B on a cyclic continuation (the workload
    class lookup-drafting exists for): tokens-per-weight-read and
    effective tok/s vs the plain path and the 1-token-per-read roofline."""
    import statistics

    import jax.numpy as jnp
    import numpy as np

    from lambdipy_tpu.models import registry

    params, rtt = _load_params_and_rtt()
    adapter = registry.get("llama3-8b").build(
        dtype="bfloat16", quant="int8", extra=dict(DIMS))
    server = adapter.make_server(params)
    import jax

    rec = {"dims": f"{DIMS['hidden']}x{DIMS['layers']}x{DIMS['vocab_size']}",
           "rtt_ms": round(rtt, 1), "k": k, "n_new": n_new,
           "platform": jax.devices()[0].platform,
           "measured_at": time.strftime("%Y-%m-%d")}
    prompt = [17, 23, 5, 99, 41, 7, 123, 64] * 4

    server.generate(prompt, max_new_tokens=n_new)  # compile + warm
    times = [_timed(lambda: server.generate(prompt, max_new_tokens=n_new))
             for _ in range(5)]
    plain_ms = max(0.1, statistics.median(times) - rtt)
    rec["plain_tok_s"] = round(n_new / (plain_ms / 1e3), 1)

    spec0, stats = server.generate_speculative(
        prompt, max_new_tokens=n_new, k=k, return_stats=True)
    ref = server.generate(prompt, max_new_tokens=n_new)
    rec["greedy_agreement"] = f"{int(np.sum(spec0[0] == ref[0]))}/{n_new}"
    times = [_timed(lambda: server.generate_speculative(
        prompt, max_new_tokens=n_new, k=k)) for _ in range(5)]
    # the host loop pays one fetch RTT per verify step (+1 for prefill)
    spec_ms = max(0.1, statistics.median(times)
                  - rtt * (stats["steps"] + 1))
    rec["spec_tok_s"] = round(n_new / (spec_ms / 1e3), 1)
    rec["spec_stats"] = stats
    from lambdipy_tpu.models.llama import LlamaConfig
    from lambdipy_tpu.utils import roofline

    cfg = LlamaConfig(**DIMS, quant="int8", dtype=jnp.bfloat16)
    rec["roofline_plain_b1_tok_s"] = round(
        roofline.llama_decode_tok_s_bound(
            cfg, batch=1, cache_len=len(prompt) + n_new // 2), 1)
    rec["speedup_vs_plain"] = round(rec["spec_tok_s"] / rec["plain_tok_s"],
                                    2)
    return rec


def measure_concurrent(n_requests: int = 8, n_new: int = 64) -> dict:
    """Continuous-batching throughput at 8B (VERDICT r5 #6): N staggered
    concurrent requests through the engine vs serving them one after
    another. Decode is weight-bytes-bound, so the engine's shared
    segment steps should put the concurrent wall close to ONE request's
    time, not N of them.

    Parity accounting: the CPU f32 tests assert BITWISE solo parity
    (same program widths, exact arithmetic). This on-chip mode instead
    reports per-request token agreement: at 8B random-init dims the
    logit argmax gaps sit at bf16 resolution, and a solo join prefills
    through the 1-row program while staggered concurrent joins
    group-prefill as one ragged b-row call — programs of different
    width legally differ in bf16 reduction order, so near-tied first
    tokens can flip (the spec mode's greedy_agreement shows the same
    physics; segment steps themselves are always slots-wide and
    identical). A loose agreement floor still catches real packing
    bugs, which corrupt rows wholesale rather than flipping
    occasional near-ties."""
    import threading

    import numpy as np

    from lambdipy_tpu.models import registry
    from lambdipy_tpu.runtime.continuous import ContinuousBatcher

    params, rtt = _load_params_and_rtt()
    adapter = registry.get("llama3-8b").build(
        dtype="bfloat16", quant="int8", extra=dict(DIMS))
    server = adapter.make_server(params)
    cb = ContinuousBatcher(server, slots=n_requests, segment=16)
    rec = {"dims": f"{DIMS['hidden']}x{DIMS['layers']}x{DIMS['vocab_size']}",
           "rtt_ms": round(rtt, 1), "n_requests": n_requests,
           "n_new": n_new, "measured_at": time.strftime("%Y-%m-%d")}
    prompts = [[11 + i, 23, 5, 99, 41, 7, 123, 64] for i in range(n_requests)]

    # warm every program (prefill bucket, pack, B-slot segment) and
    # capture the solo baselines through the SAME engine
    solo = [cb.generate(p, max_new_tokens=n_new) for p in prompts]
    t0 = time.monotonic()
    for p in prompts:
        cb.generate(p, max_new_tokens=n_new)
    rec["serial_wall_s"] = round(time.monotonic() - t0, 2)

    results: list = [None] * n_requests
    errors: list = []

    def fire(i):
        time.sleep(0.01 * i)  # staggered arrivals: mid-flight joins
        try:
            results[i] = cb.generate(prompts[i], max_new_tokens=n_new)
        except Exception as e:  # surfaced after join — a thread's
            errors.append((i, e))  # traceback otherwise only hits stderr

    # UNTIMED staggered bursts first: a concurrent burst exercises
    # programs the solo path never compiles (the b-row group-prefill
    # and mid-flight pack buckets) — on a remote-compile transport the
    # first burst pays tens of seconds of compiles and reads as a 0.3x
    # "slowdown" (measured) when what was measured was compilation.
    # Two bursts: joiner grouping is timing-dependent, so a second pass
    # catches power-of-two group buckets the first happened to miss.
    for _ in range(2):
        warm_threads = [threading.Thread(target=fire, args=(i,))
                        for i in range(n_requests)]
        for t in warm_threads:
            t.start()
        for t in warm_threads:
            t.join()
        if errors or any(r is None for r in results):
            # a failed warm burst means the timed burst would re-pay
            # first-burst compiles (the artifact this warmup exists to
            # remove) or run against a degraded engine — refuse
            raise RuntimeError(f"warm burst failed: {errors or results}")
    results = [None] * n_requests

    threads = [threading.Thread(target=fire, args=(i,))
               for i in range(n_requests)]
    before = cb.stats()  # counters are lifetime-cumulative: publish the
    t0 = time.monotonic()  # concurrent run's DELTA, not warm+serial too
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.monotonic() - t0
    for i, r in enumerate(results):  # a crashed thread must not read as
        assert r is not None, f"request {i} returned no result"
        assert np.asarray(r).shape == np.asarray(solo[i]).shape, \
            f"request {i} shape {np.asarray(r).shape}"  # a parity stat
    agree = [float(np.mean(np.asarray(results[i]) == np.asarray(solo[i])))
             for i in range(n_requests)]
    exact = sum(bool(np.array_equal(results[i], solo[i]))
                for i in range(n_requests))
    rec["rows_bitwise_equal"] = f"{exact}/{n_requests}"
    rec["solo_agreement_min"] = round(min(agree), 3)
    rec["solo_agreement_mean"] = round(sum(agree) / len(agree), 3)
    # gross-corruption backstop, deliberately loose: ONE flipped
    # near-tie early in a row legitimately de-correlates that row's
    # whole continuation, so positional agreement can be low for a
    # correct engine at random-init weights — but a packing bug is
    # systematic (every row corrupt, nothing bitwise-equal)
    if exact == 0 and rec["solo_agreement_mean"] < 0.2:
        raise AssertionError(
            f"no row matches solo and agreement is near zero — "
            f"engine corruption, not tie-flipping: {rec}")
    rec["concurrent_wall_s"] = round(wall, 2)
    rec["speedup_vs_serial"] = round(rec["serial_wall_s"] / wall, 2)
    rec["concurrent_tok_s"] = round(n_requests * n_new / wall, 1)
    after = cb.stats()
    rec["engine"] = {k: after[k] - before[k]
                     for k in ("segments_run", "rows_in_segments",
                               "requests_served")}
    return rec


def _load_params_and_rtt():
    """Shared measurement preamble: bulk-load the 8B params, force the
    async upload to actually complete with a host-observed scalar fetch
    (block_until_ready returns at submission on this transport), and
    measure the per-fetch RTT floor. ONE copy of the idiom — four
    measurement modes depend on it agreeing."""
    import jax
    import jax.numpy as jnp

    from bench import _measure_rtt_ms
    from lambdipy_tpu.bundle import flatpack

    ensure_params(params_path())
    params = flatpack.device_load(params_path())
    for leaf in jax.tree.leaves(params)[-1:]:
        float(jnp.asarray(leaf).astype(jnp.float32).sum())
    return params, _measure_rtt_ms(jax, jnp)


def measure_kv_quant(n_new: int = 64, context: int = 1024) -> dict:
    """kv_quant='int8' at real 8B dims and ~1k context (VERDICT r5 #7):
    DECODE throughput vs the bf16-KV record at the same context — the
    KV read is material in the b8 roofline there — plus the max
    logprob deviation over the emitted tokens as the 32-layer error
    bound (the toy-dims bound was only extrapolated).

    Differencing design (v2 — the first on-chip run published numbers
    ~30% over the roofline bound and taught two traps):

    - decode steps are BUCKETED: ``generate(max_new_tokens=1)`` runs a
      ``min_bucket``(=16)-step scan, so differencing full(64) against
      it spans 48 steps, not 63. Both differenced calls now use
      power-of-two ``max_new`` (64 and 32) whose step counts are exact.
    - the prompt bucket is clamped by ``max_len - steps``, so at
      max_len=1024 the two calls prefill through DIFFERENT-width
      programs and the difference is contaminated by prefill. The
      measurement dims raise max_len to 2048 (capacity only — the live
      cache array is sized prompt_bucket + steps, so the decoded
      window stays ~1k) and both calls share the identical 1024-wide
      prefill program; their difference is exactly
      ``n_new - n_new//2`` decode steps over a ~1.06k-token cache,
      with the transport RTT cancelling."""
    import statistics

    import numpy as np
    import jax.numpy as jnp

    from lambdipy_tpu.models import registry
    from lambdipy_tpu.models.llama import LlamaConfig
    from lambdipy_tpu.utils import roofline

    params, rtt = _load_params_and_rtt()
    rec: dict = {"dims": f"{DIMS['hidden']}x{DIMS['layers']}"
                         f"x{DIMS['vocab_size']}",
                 "context": context, "n_new": n_new,
                 "rtt_ms": round(rtt, 1),
                 "measured_at": time.strftime("%Y-%m-%d")}
    half = n_new // 2
    assert n_new >= 32 and n_new & (n_new - 1) == 0, \
        "n_new must be a power of two >= 32 so both step counts are exact"
    prompt = list(range(1, context - n_new + 1))  # prefill bucket = context
    mdims = dict(DIMS, max_len=max(2 * context, DIMS["max_len"]))
    variants = {
        "bf16_kv": dict(mdims),
        "int8_kv": dict(mdims, kv_quant="int8"),
    }
    outs = {}
    for name, extra in variants.items():
        adapter = registry.get("llama3-8b").build(
            dtype="bfloat16", quant="int8", extra=extra)
        server = adapter.make_server(params)
        cfg = LlamaConfig(**mdims, kv_quant=extra.get("kv_quant"),
                          quant="int8", dtype=jnp.bfloat16)
        for b in (1, 8):
            rows = [prompt] * b

            def full():
                return server.generate(rows, max_new_tokens=n_new)

            def half_call():
                return server.generate(rows, max_new_tokens=half)

            full()          # compile + warm both programs
            half_call()
            # decode-only: identical prefill program in both calls, so
            # each PAIRED difference is exactly (n_new - half) decode
            # steps. Pairing full/half back-to-back makes slow drift in
            # the prefill-dominated call time cancel within a pair
            # instead of landing in the subtraction; the pair spread is
            # published so a noisy transport shows up in the record.
            diffs = sorted(_timed(full) - _timed(half_call)
                           for _ in range(7))
            net_ms = max(0.1, statistics.median(diffs))
            rec[f"{name}_b{b}_pair_spread_ms"] = round(
                diffs[-2] - diffs[1], 1)
            bound = roofline.llama_decode_tok_s_bound(
                cfg, batch=b, cache_len=context + (n_new + half) // 2)
            rec[f"{name}_b{b}_tok_s"] = round(
                b * (n_new - half) / (net_ms / 1e3), 1)
            rec[f"{name}_b{b}_roofline_tok_s"] = round(bound, 1)
        toks, lps = server.generate(prompt, max_new_tokens=n_new,
                                    return_logprobs=True)
        outs[name] = (np.asarray(toks), np.asarray(lps))
    agree = int(np.sum(outs["bf16_kv"][0] == outs["int8_kv"][0]))
    rec["greedy_agreement"] = f"{agree}/{n_new}"
    # logprob deviation over the agreeing prefix — past the first
    # divergence the sequences differ and the comparison is moot. A
    # token-0 divergence records null rather than silently omitting
    # the bound the record exists to publish.
    same = outs["bf16_kv"][0][0] == outs["int8_kv"][0][0]
    upto = int(np.argmin(same)) if not same.all() else n_new
    if upto:
        delta = np.abs(outs["bf16_kv"][1][0][:upto]
                       - outs["int8_kv"][1][0][:upto])
        rec["max_logprob_delta"] = round(float(delta.max()), 4)
    else:
        rec["max_logprob_delta"] = None
    rec["agreeing_prefix"] = upto
    return rec


def measure_prefill(lens=(512, 1024, 2048, 4096), flash_len: int = 8192,
                    batch_len: int = 512, batch: int = 4) -> dict:
    """The prefill table (VERDICT r5 #4 + #9): dense prefill
    latency/MFU at 512/1k/2k/4k, a BATCHED 512 prefill (does MFU scale
    with rows?), and the long-context paths at 8k — flash attention
    (dense would materialize an 8.6 GB score tensor per layer) and
    chunked prefill — all at real 8B dims with an 8192 window.

    Decode-scan exclusion (v2): ``generate(max_new_tokens=1)`` runs a
    bucketed ``min_bucket``-step decode scan after the prefill — at 8B
    that's ~16 weight reads, ~180 ms, swamping short-prefill rows (the
    first published table undercalled 512-token MFU ~4x). The servers
    here run with ``min_bucket = 1`` so the scan is ONE step, and each
    row reports ``net_ms`` with that step's separately-differenced cost
    subtracted (raw timing kept as ``raw_ms``)."""
    import statistics

    import jax
    import jax.numpy as jnp

    from lambdipy_tpu.models import registry
    from lambdipy_tpu.models.llama import LlamaConfig
    from lambdipy_tpu.utils import roofline

    dims = dict(DIMS, max_len=max(flash_len, 8192))
    params, rtt = _load_params_and_rtt()
    cfg = LlamaConfig(**dims, quant="int8", dtype=jnp.bfloat16)
    rec: dict = {"dims": f"{dims['hidden']}x{dims['layers']}"
                         f"x{dims['vocab_size']}",
                 "max_len": dims["max_len"], "rtt_ms": round(rtt, 1),
                 "measured_at": time.strftime("%Y-%m-%d"),
                 "rows": []}

    step_ms = 0.0  # set once below; the one-step scan cost to subtract

    def time_prefill(server, L, b=1, label="dense"):
        rows = [list(range(1, L + 1))] * b
        t0 = time.monotonic()
        server.generate(rows, max_new_tokens=1)
        compile_s = time.monotonic() - t0
        times = [_timed(lambda: server.generate(rows, max_new_tokens=1))
                 for _ in range(3)]
        raw_ms = max(0.1, statistics.median(times) - rtt)
        net_ms = max(0.1, raw_ms - step_ms)
        cost = roofline.llama_prefill_cost(cfg, batch=b, seq_len=L)
        row = {"backend": label, "len": L, "batch": b,
               "net_ms": round(net_ms, 1), "raw_ms": round(raw_ms, 1),
               "mfu": cost.utilization(net_ms / 1e3)["mfu"],
               "compile_s": round(compile_s, 1)}
        rec["rows"].append(row)
        print(json.dumps(row), file=sys.stderr)

    adapter = registry.get("llama3-8b").build(
        dtype="bfloat16", quant="int8", extra=dims)
    server = adapter.make_server(params)
    # exact step counts for the correction differencing AND the one-step
    # scan after each timed prefill: power-of-two max_new is exact for
    # any min_bucket <= it, and min_bucket=1 makes max_new=1 exact too
    server.min_bucket = 1
    # difference the step cost at the LARGEST dense table length
    # (ADVICE r5): per-token KV at these dims is ~128 KB, so a step
    # against an 8k-deep cache reads ~12% more than one against 512 —
    # differencing at the small end under-subtracted from exactly the
    # long rows where the step is largest, inflating their net_ms.
    # Differencing at max(lens) is exact for the deepest dense row; the
    # residual biases are bounded by that same ~12%-of-one-step: short
    # rows are OVER-subtracted (their published MFU reads slightly
    # HIGH — step_ms is ~2% of a 512 prefill, so the bias is <1% of
    # MFU), and the flash row at flash_len > max(lens) is still
    # slightly under-subtracted (its dense-server step can't be
    # measured at 8k depth — that's the score tensor flash exists to
    # avoid).
    L0 = max(lens)
    rows0 = [list(range(1, L0 + 1))]
    server.generate(rows0, max_new_tokens=32)  # compile + warm
    server.generate(rows0, max_new_tokens=1)
    t32 = statistics.median(
        _timed(lambda: server.generate(rows0, max_new_tokens=32))
        for _ in range(5))
    t1 = statistics.median(
        _timed(lambda: server.generate(rows0, max_new_tokens=1))
        for _ in range(5))
    # 31 decode steps separate the two calls (identical prefill program)
    step_ms = max(0.0, (t32 - t1) / 31.0)
    rec["decode_step_ms"] = round(step_ms, 2)
    print(json.dumps({"decode_step_ms": rec["decode_step_ms"]}),
          file=sys.stderr)
    for L in lens:
        time_prefill(server, L)
    time_prefill(server, batch_len, b=batch)  # batched prefill
    # flash attention at 8k (the O(S)-memory fallback's reason to exist)
    fl = registry.get("llama3-8b").build(
        dtype="bfloat16", quant="int8",
        extra=dict(dims, attn_backend="flash"))
    fl_server = fl.make_server(params)
    fl_server.min_bucket = 1
    time_prefill(fl_server, flash_len, label="flash")
    # chunked prefill at 8k via the prefix machinery (512-token chunks)
    ck_server = adapter.make_server(params, prefill_chunk=512)
    long_tokens = list(range(1, flash_len + 1))
    ck_server.cache_prefix(long_tokens[:1024])  # compile first+ext

    def chunked_once():
        key = ck_server.cache_prefix(long_tokens)
        # cache_prefix only SUBMITS the chunk walk (and on this
        # transport block_until_ready returns at submission): fetch a
        # scalar reduction of the last layer's cache so the timed
        # region observes the device actually finish, matching
        # time_prefill's device_get methodology
        with ck_server._prefix_lock:
            cache, _ = ck_server._prefixes.pop(key)  # pop: re-time fresh
        leaf = jax.tree.leaves(cache)[-1]
        float(jnp.asarray(leaf).astype(jnp.float32).sum())

    t0 = time.monotonic()
    chunked_once()
    net_ms = max(0.1, (time.monotonic() - t0) * 1e3 - rtt)
    cost = roofline.llama_prefill_cost(cfg, batch=1, seq_len=flash_len)
    row = {"backend": "chunked512", "len": flash_len, "batch": 1,
           "net_ms": round(net_ms, 1),
           "mfu": cost.utilization(net_ms / 1e3)["mfu"]}
    rec["rows"].append(row)
    print(json.dumps(row), file=sys.stderr)
    # scaling decomposition (the "where do the missing MFU go" analysis,
    # VERDICT r5 #4): fit t(s) = c0 + c1*s + c2*s^2 over the dense b=1
    # points. The linear term is the weight-read + per-token matmul
    # work, the quadratic term is attention score/AV work, the constant
    # is dispatch/lm_head/fixed overhead — their shares at each length
    # say whether low prefill MFU is an attention problem (quadratic
    # share high) or an overhead problem (constant share high).
    dense = [r for r in rec["rows"] if r["backend"] == "dense"
             and r["batch"] == 1]
    # >= 4 points: with exactly 3 the quadratic fit degenerates to
    # interpolation and sample jitter maps straight into the published
    # coefficients (the decomposition needs a residual DOF to mean
    # anything)
    if len(dense) >= 4:
        import numpy as np

        s_arr = np.array([r["len"] for r in dense], float)
        t_arr = np.array([r["net_ms"] for r in dense], float)
        c2, c1, c0 = (float(c) for c in np.polyfit(s_arr, t_arr, 2))
        rec["scaling_fit"] = {
            "const_ms": round(c0, 2), "linear_ms_per_tok": round(c1, 4),
            "quad_ms_per_tok2": round(c2, 8),
            "shares_at": {
                str(int(s)): {
                    "const": round(c0 / t, 2),
                    "linear": round(c1 * s / t, 2),
                    "quad": round(c2 * s * s / t, 2)}
                for s, t in zip(s_arr, t_arr)},
        }
        print(json.dumps({"scaling_fit": rec["scaling_fit"]}),
              file=sys.stderr)
    return rec


def _publish(update) -> None:
    """Apply ``update(published, config5)`` to BASELINE.json atomically
    enough for this single-writer script (one read-modify-write)."""
    from publish_util import write_doc

    path = REPO / "BASELINE.json"
    doc = json.loads(path.read_text())
    pub = doc.setdefault("published", {})
    update(pub, pub.setdefault("config5", {}))
    write_doc(doc, path)
    print(f"published -> {path}", file=sys.stderr)


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", default="1,8")
    # None = "flag omitted": modes pick their own default (64, except
    # kv-quant's 128) and an EXPLICIT --n-new always wins — keying the
    # kv-quant override on the default value made --n-new 64 unreachable
    ap.add_argument("--n-new", type=int, default=None)
    ap.add_argument("--cold-start", action="store_true",
                    help="measure the build->deploy->invoke cold start "
                         "instead of decode throughput")
    ap.add_argument("--speculative", action="store_true",
                    help="measure speculative vs plain b1 decode")
    ap.add_argument("--k", type=int, default=8,
                    help="draft length for --speculative")
    ap.add_argument("--concurrent", action="store_true",
                    help="measure N staggered requests through the "
                         "continuous-batching engine vs serial")
    ap.add_argument("--n-requests", type=int, default=8)
    ap.add_argument("--prefill-table", action="store_true",
                    help="measure the prefill table: dense 512/1k/4k, "
                         "batched 512, flash + chunked at 8k")
    ap.add_argument("--kv-quant", action="store_true",
                    help="measure int8-KV vs bf16-KV decode at 1k "
                         "context + the 32-layer logprob error bound")
    ap.add_argument("--publish", action="store_true",
                    help="record into BASELINE.json published.config5")
    args = ap.parse_args()
    n_new = 64 if args.n_new is None else args.n_new
    if args.prefill_table:
        record = measure_prefill()
        print(json.dumps(record, indent=2))
        if args.publish:
            _publish(lambda pub, c5: c5.__setitem__("prefill", record))
        return 0
    if args.kv_quant:
        # the differenced signal is (n_new/2) decode steps; 128 doubles
        # it vs the shared 64 default without moving the ~1k window much
        # — but only when --n-new was OMITTED (an explicit value wins)
        record = measure_kv_quant(
            n_new=128 if args.n_new is None else args.n_new)
        print(json.dumps(record, indent=2))
        if args.publish:
            _publish(lambda pub, c5: c5.__setitem__("kv_int8", record))
        return 0
    if args.concurrent:
        record = measure_concurrent(n_requests=args.n_requests,
                                    n_new=n_new)
        print(json.dumps(record, indent=2))
        if args.publish:
            _publish(lambda pub, c5: c5.__setitem__("concurrent", record))
        return 0
    if args.speculative:
        record = measure_speculative(n_new=n_new, k=args.k)
        print(json.dumps(record, indent=2))
        if args.publish:
            _publish(lambda pub, c5: c5.__setitem__("speculative", record))
        return 0
    if args.cold_start:
        record = measure_cold_start()
        print(json.dumps(record, indent=2))
        if args.publish:
            _publish(lambda pub, c5: c5.update(
                {f"cold_{k}" if k in ("build_s",) else k: v
                 for k, v in record.items()
                 if k not in ("dims", "measured_at")}))
        return 0
    batches = tuple(int(b) for b in args.batch.split(","))
    record = measure(batches=batches, n_new=n_new)
    print(json.dumps(record, indent=2))
    if args.publish:
        def replace(pub, c5):
            from publish_util import MICRO_RECIPE, RECIPE_8B

            # keep the micro exemplar visible beside the real-dims record,
            # but any dict-valued sub-records in config5 are 8B-mode
            # output (speculative/concurrent/kv_int8/prefill/cold stages)
            # and stay with config5 rather than moving under the micro key
            if c5.get("recipe") == MICRO_RECIPE:
                pub["config5_micro"] = {
                    k: v for k, v in c5.items() if not isinstance(v, dict)}
                c5 = pub["config5"] = {
                    k: v for k, v in c5.items() if isinstance(v, dict)}
            # refresh semantics for the decode-owned scalars (incl. the
            # conditional param_gen_s): drop them first so a partial run
            # (e.g. --batch 1, or one hitting the flatpack cache) can't
            # leave stale metrics stamped with the new measured_at — then
            # merge, preserving the other modes' sub-records
            import re

            for k in [k for k in c5
                      if re.match(r"b\d+_|prefill_|param_gen_s", k)]:
                del c5[k]
            record["recipe"] = RECIPE_8B
            c5.update(record)

        _publish(replace)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
