"""Ring attention: sequence/context parallelism over the ``sp`` mesh axis.

Long-context attention where the sequence is sharded across devices and
K/V blocks rotate around the ring via ``lax.ppermute`` (one ICI hop per
step) while each device accumulates online-softmax partial results for its
local Q block — compute overlaps the rotation, full attention is recovered
exactly, and no device ever materializes more than (s/sp)^2 scores. This is
the blockwise/ring formulation (Liu et al.) expressed the TPU way:
``shard_map`` + XLA collectives over the mesh, not a hand-rolled transport
(SURVEY.md §3.2, §6 long-context row).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from lambdipy_tpu.parallel.mesh import shard_map_compat

NEG_INF = -1e30


def _block_attend(q, k, v, mask, scale):
    """One blockwise attention contribution. q: [b,sq,h,d]; k/v: [b,sk,h,d];
    mask: bool broadcastable to [b,h,sq,sk], or None. Returns (m, l, acc)
    partials in f32."""
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
    if mask is not None:
        s = jnp.where(mask, s, NEG_INF)
    m = jnp.max(s, axis=-1)  # [b,h,q]
    # guard fully-masked rows: exp(NEG_INF - NEG_INF) would give 1s
    m_safe = jnp.where(m <= NEG_INF / 2, 0.0, m)
    p = jnp.exp(s - m_safe[..., None])
    p = jnp.where(s <= NEG_INF / 2, 0.0, p)
    l = jnp.sum(p, axis=-1)  # [b,h,q]
    acc = jnp.einsum("bhqk,bkhd->bqhd", p.astype(v.dtype), v).astype(jnp.float32)
    return m_safe, l, acc


def _combine(m1, l1, acc1, m2, l2, acc2):
    """Merge two online-softmax partials."""
    m = jnp.maximum(m1, m2)
    a1 = jnp.exp(m1 - m)
    a2 = jnp.exp(m2 - m)
    l = l1 * a1 + l2 * a2
    # broadcast [b,h,q] coefficients onto [b,q,h,d] accumulators
    def bcast(a):
        return jnp.transpose(a, (0, 2, 1))[..., None]
    acc = acc1 * bcast(a1) + acc2 * bcast(a2)
    return m, l, acc


def _ring_attention_local(q, k, v, km=None, *, axis_name: str, causal: bool,
                          scale: float, vary_axes: tuple[str, ...] = ()):
    """Per-shard body (runs inside shard_map). q/k/v: [b, s_local, h, d];
    km: [b, s_local] bool key-validity block (padding mask) or None — it
    rotates around the ring with its k/v block."""
    sp = jax.lax.psum(1, axis_name)
    my = jax.lax.axis_index(axis_name)
    b, sq, h, d = q.shape

    causal_block = jnp.tril(jnp.ones((sq, sq), jnp.bool_)) if causal else None
    perm = [(i, (i + 1) % sp) for i in range(sp)]

    # mark the initial accumulators as varying over the ring axis so the
    # scan carry type matches its device-varying outputs (jax vma
    # tracking; identity on 0.4.x, which tracks none)
    def varying(x):
        from lambdipy_tpu.parallel.mesh import pcast_varying

        return pcast_varying(x, vary_axes or (axis_name,))

    m0 = varying(jnp.full((b, h, sq), NEG_INF, jnp.float32))
    l0 = varying(jnp.zeros((b, h, sq), jnp.float32))
    acc0 = varying(jnp.zeros((b, sq, h, d), jnp.float32))

    def step(carry, i):
        m, l, acc, kb, vb, kmb = carry
        src = (my - i) % sp  # which global block this kv currently is
        if causal:
            # src < my: fully visible; src == my: causal; src > my: skip
            pos = jnp.where(src < my, jnp.ones((sq, sq), jnp.bool_),
                            jnp.where(src == my, causal_block,
                                      jnp.zeros((sq, sq), jnp.bool_)))
            mask = pos[None, None]  # [1,1,sq,sk]
        else:
            mask = None
        if kmb is not None:
            kmask = kmb[:, None, None, :]  # [b,1,1,sk]
            mask = kmask if mask is None else mask & kmask
        bm, bl, bacc = _block_attend(q, kb, vb, mask, scale)
        m, l, acc = _combine(m, l, acc, bm, bl, bacc)
        kb = jax.lax.ppermute(kb, axis_name, perm)
        vb = jax.lax.ppermute(vb, axis_name, perm)
        if kmb is not None:
            kmb = jax.lax.ppermute(kmb, axis_name, perm)
        return (m, l, acc, kb, vb, kmb), None

    carry0 = (m0, l0, acc0, k, v, None if km is None else km)
    (m, l, acc, _, _, _), _ = jax.lax.scan(step, carry0, jnp.arange(sp))
    l = jnp.maximum(l, 1e-30)
    out = acc / jnp.transpose(l, (0, 2, 1))[..., None]
    return out.astype(q.dtype)


def _sp_chunk_local(q, k, v, mask, *, nblocks: int, scale: float,
                    vary_axes: tuple[str, ...]):
    """Per-shard body for :func:`sp_chunk_attention` (runs inside
    shard_map). q: [b, sq_local, h, d]; k/v: [b, t, h, d] (the FULL,
    replicated cache); mask: [b, sq_local, t] bool. The key axis is
    walked in ``nblocks`` blocks through the same ``_block_attend`` /
    ``_combine`` online-softmax pair the ring path uses, so the combine
    math is block-exact and per-shard score memory is
    (sq/sp) x ceil(t/nblocks), never the full (sq x t) sheet."""
    from lambdipy_tpu.parallel.mesh import pcast_varying

    b, sq, h, d = q.shape
    t = k.shape[1]
    m = pcast_varying(jnp.full((b, h, sq), NEG_INF, jnp.float32), vary_axes)
    l = pcast_varying(jnp.zeros((b, h, sq), jnp.float32), vary_axes)
    acc = pcast_varying(jnp.zeros((b, sq, h, d), jnp.float32), vary_axes)
    kb = -(-t // nblocks)  # ceil
    for i in range(nblocks):
        lo = i * kb
        hi = min(t, lo + kb)
        if lo >= hi:
            break
        bm, bl, bacc = _block_attend(q, k[:, lo:hi], v[:, lo:hi],
                                     mask[:, None, :, lo:hi], scale)
        m, l, acc = _combine(m, l, acc, bm, bl, bacc)
    l = jnp.maximum(l, 1e-30)
    out = acc / jnp.transpose(l, (0, 2, 1))[..., None]
    return out.astype(q.dtype)


def sp_chunk_attention(q, k, v, mask, mesh: Mesh, *, axis: str = "sp",
                       scale: float | None = None):
    """Sequence-parallel prefill-CHUNK attention: the chunk's queries are
    sharded over ``axis`` while the full K/V cache (prefix + this chunk,
    already written at the cache index) stays replicated — each shard
    owns s/sp query rows and attends the whole key range under the
    caller's validity mask. This is the continuation-chunk member of the
    whole-prompt sp-prefill family: the first chunk has no cache and
    ring-shards both operands (:func:`ring_attention`); every later
    chunk reads a cache that decode keeps replicated anyway, so only the
    query/score side shards and no collective is needed beyond the
    out-spec gather.

    q: [b, s, h, d] with ``s`` divisible by the ``axis`` size;
    k/v: [b, t, kvh, d]; mask: [b, s, t] bool (True = attend).
    """
    h, kvh = q.shape[2], k.shape[2]
    if kvh != h:
        rep = h // kvh
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    sp = mesh.shape[axis]
    if q.shape[1] % sp:
        raise ValueError(
            f"sp_chunk_attention: chunk width {q.shape[1]} not divisible "
            f"by {axis}={sp}")
    batch_axes = tuple(a for a in ("dp", "fsdp") if a in mesh.axis_names)
    bspec = batch_axes if batch_axes else None
    qspec = P(bspec, axis, None, None)
    kspec = P(bspec, None, None, None)
    mspec = P(bspec, axis, None)
    local = partial(_sp_chunk_local, nblocks=sp, scale=scale,
                    vary_axes=batch_axes + (axis,))
    fn = shard_map_compat(local, mesh=mesh,
                          in_specs=(qspec, kspec, kspec, mspec),
                          out_specs=qspec)
    return fn(q, k, v, mask)


def ring_attention(q, k, v, mesh: Mesh, *, axis: str = "sp",
                   causal: bool = True, scale: float | None = None,
                   kv_mask=None):
    """Full attention over sequence-sharded q/k/v: [b, s, h, d] with the
    ``s`` dim sharded over ``axis``. GQA kv heads are broadcast first.

    kv_mask: optional [b, s] bool key-validity (padding) mask, sharded like
    the sequence; masked key positions are excluded on every ring step, so
    padded batches attend identically to the dense backend."""
    h, kvh = q.shape[2], k.shape[2]
    if kvh != h:
        rep = h // kvh
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    batch_axes = tuple(a for a in ("dp", "fsdp") if a in mesh.axis_names)
    spec = P(batch_axes if batch_axes else None, axis, None, None)
    local = partial(_ring_attention_local, axis_name=axis, causal=causal,
                    scale=scale, vary_axes=batch_axes + (axis,))
    if kv_mask is not None:
        mspec = P(batch_axes if batch_axes else None, axis)
        fn = shard_map_compat(local, mesh=mesh,
                           in_specs=(spec, spec, spec, mspec), out_specs=spec)
        return fn(q, k, v, kv_mask)
    fn = shard_map_compat(local, mesh=mesh, in_specs=(spec, spec, spec),
                       out_specs=spec)
    return fn(q, k, v)
