"""Sharding rules: parameter-path patterns -> PartitionSpec.

Models stay sharding-agnostic (plain flax modules); the mapping from
parameter paths to mesh axes lives here, so the same model runs single-chip
(all specs replicated), TP-served on v5e-4, or FSDP-trained, by swapping
rule sets. XLA inserts the collectives implied by the shardings (the
scaling-book recipe: pick a mesh, annotate, let XLA place all-gathers /
reduce-scatters on ICI).
"""

from __future__ import annotations

import fnmatch
from dataclasses import dataclass

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


@dataclass(frozen=True)
class ShardingRules:
    """Ordered (path-glob, PartitionSpec) rules; first match wins.

    Paths are '/'-joined pytree key paths, e.g.
    ``params/layers_0/attn/q_proj/kernel``.
    """

    rules: tuple[tuple[str, P], ...]
    default: P = P()

    def spec_for(self, path: str) -> P:
        for pattern, spec in self.rules:
            if fnmatch.fnmatch(path, pattern):
                return spec
        return self.default


def _path_str(key_path) -> str:
    parts = []
    for k in key_path:
        if hasattr(k, "key"):
            parts.append(str(k.key))
        elif hasattr(k, "idx"):
            parts.append(str(k.idx))
        else:
            parts.append(str(k))
    return "/".join(parts)


def _filter_spec(spec: P, mesh: Mesh, ndim: int) -> P:
    """Drop axes not present in the mesh (size-1 axes are omitted from Mesh
    by make_mesh) and truncate/pad to the array rank, so one rule set works
    across mesh shapes."""
    names = set(mesh.axis_names)

    def keep(entry):
        if entry is None:
            return None
        if isinstance(entry, (tuple, list)):
            kept = tuple(e for e in entry if e in names)
            return kept if kept else None
        return entry if entry in names else None

    entries = [keep(e) for e in spec]
    entries = entries[:ndim] + [None] * max(0, ndim - len(entries))
    return P(*entries)


def named_sharding(mesh: Mesh, *entries) -> NamedSharding:
    return NamedSharding(mesh, _filter_spec(P(*entries), mesh, len(entries)))


def shard_params(params, mesh: Mesh, rules: ShardingRules):
    """Device-put a parameter pytree according to path rules."""

    def place(key_path, leaf):
        spec = _filter_spec(rules.spec_for(_path_str(key_path)), mesh, leaf.ndim)
        return jax.device_put(leaf, NamedSharding(mesh, spec))

    return jax.tree_util.tree_map_with_path(place, params)


def param_shardings(params, mesh: Mesh, rules: ShardingRules):
    """The NamedSharding pytree for ``params`` (for jit in_shardings)."""

    def spec(key_path, leaf):
        return NamedSharding(
            mesh, _filter_spec(rules.spec_for(_path_str(key_path)), mesh, leaf.ndim))

    return jax.tree_util.tree_map_with_path(spec, params)


def shard_batch(batch, mesh: Mesh, axis: str = "dp"):
    """Shard the leading (batch) dim of every leaf over the data axes."""

    def place(leaf):
        spec = _filter_spec(P(axis), mesh, leaf.ndim)
        return jax.device_put(leaf, NamedSharding(mesh, spec))

    return jax.tree_util.tree_map(place, batch)
