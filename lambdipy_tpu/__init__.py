"""lambdipy-tpu: a TPU-native serverless bundle framework.

Re-implements the capabilities of the reference packaging tool
(``customink/lambdipy`` — per-package build recipes, prebuilt-artifact fetch,
build-container compile path, strip/prune size pass, Lambda packaging; see
SURVEY.md §1-§4) as an idiomatic TPU framework:

- recipes gain jax/flax and torch-xla model variants (SURVEY.md §2 table),
- the build container becomes an isolated local venv modeled on the JAX AI
  TPU image procedure (SURVEY.md §3.4),
- the prune pass understands and preserves the XLA/PJRT/libtpu shared
  objects (SURVEY.md §3.3),
- bundles carry model params (orbax) and a persistent XLA compilation cache
  so cold start beats the <10 s target (BASELINE.md),
- a serve runtime boots bundles on a TPU chip and serves ``/invoke``,
- model payloads (ResNet-50 / BERT / Llama) are built SPMD-first with
  ``jax.sharding.Mesh`` + tensor/sequence parallelism over ICI.

Subpackages are imported lazily: importing :mod:`lambdipy_tpu` must stay
cheap because interpreter+import time is part of the serve cold-start budget
(BASELINE.md: ~10.5 s measured floor).
"""

from __future__ import annotations

import importlib

__version__ = "0.1.0"

_SUBMODULES = (
    "recipes",
    "resolve",
    "buildengine",
    "bundle",
    "runtime",
    "models",
    "ops",
    "parallel",
    "train",
    "utils",
)


def __getattr__(name: str):
    if name in _SUBMODULES:
        mod = importlib.import_module(f"{__name__}.{name}")
        globals()[name] = mod
        return mod
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


def __dir__():
    return sorted(list(globals()) + list(_SUBMODULES))
