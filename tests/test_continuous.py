"""Continuous (in-flight) batching: requests join a running decode at
segment boundaries with bitwise solo parity (VERDICT r3 missing #3)."""

import threading
import time
from concurrent.futures import ThreadPoolExecutor

import numpy as np
import pytest

from lambdipy_tpu.runtime.continuous import ContinuousBatcher

# tiny_server: the session-scoped shared LlamaServer from conftest.py
# (one compiled-program cache across the continuous-engine modules)


def test_staggered_concurrent_requests_match_solo(tiny_server):
    """8 staggered concurrent requests produce exactly their solo outputs
    while SHARING segment steps (the whole point: rows ride the same
    device calls instead of queueing end-to-end)."""
    cb = ContinuousBatcher(tiny_server, slots=8, segment=8)
    prompts = [[1 + i, 2 + i, 3 + i, 5] for i in range(8)]
    n = 16
    solo = [tiny_server.generate(p, max_new_tokens=n) for p in prompts]

    results = [None] * 8

    def run(i):
        time.sleep(0.02 * i)  # staggered arrivals, mid-flight joins
        results[i] = cb.generate(prompts[i], max_new_tokens=n)

    with ThreadPoolExecutor(max_workers=8) as ex:
        list(ex.map(run, range(8)))

    for i in range(8):
        np.testing.assert_array_equal(results[i], solo[i],
                                      err_msg=f"request {i} diverged")
    stats = cb.stats()
    # solo would cost 8 requests x ceil(16/8) = 16 segment runs; sharing
    # must beat that, and rows-per-segment > 1 proves actual fusion
    assert stats["segments_run"] < 16, stats
    assert stats["rows_in_segments"] > stats["segments_run"], stats
    assert stats["requests_served"] == 8, stats


def test_midflight_join(tiny_server):
    """A request arriving while another is decoding joins at the next
    segment boundary instead of waiting for the whole decode."""
    cb = ContinuousBatcher(tiny_server, slots=4, segment=4)
    long_prompt, short_prompt = [1, 2, 3, 4, 5], [9, 8, 7]
    n_long, n_short = 24, 8
    solo_long = tiny_server.generate(long_prompt, max_new_tokens=n_long)
    solo_short = tiny_server.generate(short_prompt, max_new_tokens=n_short)

    out = {}

    def late():
        time.sleep(0.05)
        out["short"] = cb.generate(short_prompt, max_new_tokens=n_short)

    t = threading.Thread(target=late)
    t.start()
    out["long"] = cb.generate(long_prompt, max_new_tokens=n_long)
    t.join()
    np.testing.assert_array_equal(out["long"], solo_long)
    np.testing.assert_array_equal(out["short"], solo_short)


def test_mixed_eos_rows_share_the_batch(tiny_server):
    """eos is host-side: rows with DIFFERENT eos ids fuse into one batch
    and still match their solo outputs (including the eos filler tail)."""
    cb = ContinuousBatcher(tiny_server, slots=4, segment=4)
    # find a token each row actually emits, to use as its eos
    free = tiny_server.generate([5, 6, 7, 8], max_new_tokens=8)[0]
    eos_a = int(free[2])
    free_b = tiny_server.generate([1, 2], max_new_tokens=8)[0]
    eos_b = int(free_b[3])
    solo_a = tiny_server.generate([5, 6, 7, 8], max_new_tokens=8,
                                  eos_id=eos_a)
    solo_b = tiny_server.generate([1, 2], max_new_tokens=8, eos_id=eos_b)

    with ThreadPoolExecutor(max_workers=2) as ex:
        fa = ex.submit(cb.generate, [5, 6, 7, 8], max_new_tokens=8,
                       eos_id=eos_a)
        fb = ex.submit(cb.generate, [1, 2], max_new_tokens=8, eos_id=eos_b)
        np.testing.assert_array_equal(fa.result(), solo_a)
        np.testing.assert_array_equal(fb.result(), solo_b)


def test_logprobs_ride_continuous_batching(tiny_server):
    cb = ContinuousBatcher(tiny_server, slots=2, segment=4)
    toks, lps = cb.generate([1, 2, 3], max_new_tokens=8,
                            return_logprobs=True)
    st, sl = tiny_server.generate([1, 2, 3], max_new_tokens=8,
                                  return_logprobs=True)
    np.testing.assert_array_equal(toks, st)
    np.testing.assert_allclose(lps, sl, rtol=1e-5, atol=1e-6)


def test_sampled_requests_batch_with_parity(tiny_server):
    """Sampled (temperature > 0) requests ride the engine (VERDICT r5
    #2) and every row — sampled next to greedy next to differently-
    knobbed sampled traffic — produces exactly its solo output: per-row
    knob operands + seed-derived per-row PRNG chains make a row's
    sample independent of batch composition."""
    cb = ContinuousBatcher(tiny_server, slots=4, segment=4)
    reqs = [
        dict(prompt=[1, 2, 3], kw=dict(temperature=0.9, seed=7)),
        dict(prompt=[9, 8, 7, 6], kw={}),  # greedy neighbor
        dict(prompt=[4, 4], kw=dict(temperature=1.5, top_k=3, seed=11)),
        dict(prompt=[5, 6, 7], kw=dict(temperature=0.7, top_p=0.9,
                                       seed=3)),
    ]
    solo = [tiny_server.generate(r["prompt"], max_new_tokens=8, **r["kw"])
            for r in reqs]
    with ThreadPoolExecutor(max_workers=4) as ex:
        futs = [ex.submit(cb.generate, r["prompt"], max_new_tokens=8,
                          **r["kw"]) for r in reqs]
        for i, f in enumerate(futs):
            np.testing.assert_array_equal(f.result(), solo[i],
                                          err_msg=f"request {i} diverged")
    stats = cb.stats()
    assert stats["requests_served"] == 4, stats
    assert stats["rows_in_segments"] > stats["segments_run"], stats


def test_over_cache_len_falls_back_to_solo(tiny_server):
    """A request over the engine's capped cache_len serves SOLO (the
    bundle could serve it before continuous mode was enabled — the cap
    must not become a client-visible error, ADVICE r4); what the model
    itself can't hold still raises."""
    cb = ContinuousBatcher(tiny_server, slots=2, segment=4, cache_len=32)
    prompt = list(range(1, 30))
    out = cb.generate(prompt, max_new_tokens=16)
    np.testing.assert_array_equal(
        out, tiny_server.generate(prompt, max_new_tokens=16))
    assert cb.stats()["segments_run"] == 0  # never touched the engine
    with pytest.raises(ValueError):  # beyond max_len: still an error
        cb.generate(list(range(1, 100)), max_new_tokens=120)


def test_engine_failure_surfaces_to_callers(tiny_server, monkeypatch):
    """An engine crash must fail pending requests, not hang them, and the
    engine must restart cleanly afterwards."""
    cb = ContinuousBatcher(tiny_server, slots=2, segment=4)

    def boom(self):
        raise RuntimeError("injected engine failure")

    monkeypatch.setattr(ContinuousBatcher, "_segment_fn", boom)
    with pytest.raises(RuntimeError, match="injected"):
        cb.generate([1, 2, 3], max_new_tokens=8)
    monkeypatch.undo()
    out = cb.generate([1, 2, 3], max_new_tokens=8)
    np.testing.assert_array_equal(
        out, tiny_server.generate([1, 2, 3], max_new_tokens=8))


def test_more_requests_than_slots(tiny_server):
    """Joiners beyond the slot count wait for a free slot and still
    complete correctly (slot turnover mid-engine-run)."""
    cb = ContinuousBatcher(tiny_server, slots=2, segment=4)
    prompts = [[1 + i, 3, 5] for i in range(5)]
    solo = [tiny_server.generate(p, max_new_tokens=8) for p in prompts]
    with ThreadPoolExecutor(max_workers=5) as ex:
        futs = [ex.submit(cb.generate, p, max_new_tokens=8)
                for p in prompts]
        for i, f in enumerate(futs):
            np.testing.assert_array_equal(f.result(), solo[i],
                                          err_msg=f"request {i}")


@pytest.mark.slow
def test_http_continuous_batching_end_to_end(tmp_path):
    """batch_mode='continuous' through the real bundle + threaded HTTP
    server: concurrent greedy invokes ride shared segment steps and
    /metrics exposes the engine counters."""
    import json
    import urllib.request

    from tests.test_runtime import make_model_bundle
    from lambdipy_tpu.runtime.server import BundleServer

    bundle = make_model_bundle(
        tmp_path, model="llama-tiny",
        handler="lambdipy_tpu.runtime.handlers:generate_handler",
        extra={"max_new_tokens": "8", "batch_mode": "continuous",
               "batch_max": "4", "batch_segment": "4"})
    server = BundleServer(bundle, port=0).start_background()
    base = f"http://127.0.0.1:{server.port}"

    def post(payload):
        req = urllib.request.Request(
            f"{base}/invoke", data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=120) as r:
            return json.loads(r.read())

    try:
        ref = post({"tokens": [1, 2, 3]})
        assert ref["ok"], ref
        with ThreadPoolExecutor(max_workers=4) as ex:
            futs = [ex.submit(post, {"tokens": [1, 2, 3 + i]})
                    for i in range(4)]
            results = [f.result() for f in futs]
        assert all(r["ok"] and r["n_new"] == 8 for r in results)
        # same prompt, concurrent or not -> same tokens
        again = post({"tokens": [1, 2, 3]})
        assert again["tokens"] == ref["tokens"]
        with urllib.request.urlopen(f"{base}/metrics", timeout=30) as r:
            metrics = json.loads(r.read())
        engine = metrics["handler"]["batching"]
        assert engine["mode"] == "continuous"
        assert engine["requests_served"] >= 6
        assert engine["rows_in_segments"] > engine["segments_run"], engine
    finally:
        server.stop()


def test_stream_rides_the_engine(tiny_server):
    """A streamed request joins the SHARED engine batch (VERDICT r5
    #3b): its chunk concatenation equals the fused output while another
    request decodes concurrently in the same segments."""
    cb = ContinuousBatcher(tiny_server, slots=4, segment=4)
    fused = tiny_server.generate([1, 2, 3], max_new_tokens=11)
    with ThreadPoolExecutor(max_workers=2) as ex:
        f_other = ex.submit(cb.generate, [9, 8, 7], max_new_tokens=8)
        chunks = list(cb.generate_stream([1, 2, 3], max_new_tokens=11))
        other = f_other.result()
    np.testing.assert_array_equal(np.concatenate(chunks, axis=1), fused)
    np.testing.assert_array_equal(
        other, tiny_server.generate([9, 8, 7], max_new_tokens=8))
    stats = cb.stats()
    assert stats["rows_in_segments"] > stats["segments_run"], stats


def assert_stream_eos_latch(server, cb):
    """Shared scenario (also run at depth 3 by the pipelined-engine
    module): streaming latches eos with fused-path parity."""
    fused = server.generate([1, 2, 3], max_new_tokens=11)
    eos = int(fused[0, 1])
    ref = server.generate([1, 2, 3], max_new_tokens=11, eos_id=eos)
    got = np.concatenate(list(cb.generate_stream(
        [1, 2, 3], max_new_tokens=11, eos_id=eos)), axis=1)
    assert got.shape[1] < 11  # stopped at a segment boundary
    np.testing.assert_array_equal(got, ref[:, :got.shape[1]])


def test_stream_eos_and_logprobs_through_engine(tiny_server):
    """Engine streaming latches eos with fused-path parity and carries
    logprobs."""
    cb = ContinuousBatcher(tiny_server, slots=2, segment=4)
    assert_stream_eos_latch(tiny_server, cb)
    ft, fl = tiny_server.generate([5, 6], max_new_tokens=8,
                                  return_logprobs=True)
    pairs = list(cb.generate_stream([5, 6], max_new_tokens=8,
                                    return_logprobs=True))
    np.testing.assert_array_equal(
        np.concatenate([p[0] for p in pairs], axis=1), ft)
    np.testing.assert_allclose(
        np.concatenate([p[1] for p in pairs], axis=1), fl,
        rtol=1e-5, atol=1e-6)


def assert_prefix_join_parity(server, cb):
    """Shared scenario (also run at depth 3 by the pipelined-engine
    module): a prefix-cached row's engine output equals the full-prompt
    fused output, streamed and not, while sharing segments with other
    traffic."""
    prefix = list(range(1, 20))
    full = server.generate(prefix + [4, 5], max_new_tokens=8)
    with ThreadPoolExecutor(max_workers=2) as ex:
        f_other = ex.submit(cb.generate, [9, 8, 7], max_new_tokens=8)
        via = cb.generate([4, 5], max_new_tokens=8, prefix=prefix)
        f_other.result()
    np.testing.assert_array_equal(via, full)
    st = np.concatenate(list(cb.generate_stream(
        [4, 5], max_new_tokens=8, prefix=prefix)), axis=1)
    np.testing.assert_array_equal(st, full)


def test_prefix_rows_join_the_engine(tiny_server):
    """A prefix-cached request packs its continuation carry into an
    engine slot (VERDICT r5 #3c): output equals the full-prompt fused
    output, streamed and not, while sharing segments with other
    traffic; a cache-capped engine falls back solo instead."""
    cb = ContinuousBatcher(tiny_server, slots=4, segment=4)
    assert_prefix_join_parity(tiny_server, cb)
    prefix = list(range(1, 20))
    full = tiny_server.generate(prefix + [4, 5], max_new_tokens=8)
    capped = ContinuousBatcher(tiny_server, slots=2, segment=4,
                               cache_len=32)
    np.testing.assert_array_equal(
        capped.generate([4, 5], max_new_tokens=8, prefix=prefix), full)
    assert capped.stats()["segments_run"] == 0  # solo fallback


def test_group_prefill_packs_waiting_joiners(tiny_server):
    """Short-prompt joiners enqueue raw and the engine prefills them in
    ONE ragged call (VERDICT r5 #4 batched prefill): parity per row and
    fewer prefill programs than requests."""
    cb = ContinuousBatcher(tiny_server, slots=4, segment=4)
    reqs = [([1, 2, 3], dict(temperature=0.9, seed=7)),
            ([9, 8, 7, 6], {}),
            ([4, 4], dict(temperature=1.5, top_k=3, seed=11)),
            ([5, 6, 7], {})]
    solo = [tiny_server.generate(p, max_new_tokens=8, **kw)
            for p, kw in reqs]
    with ThreadPoolExecutor(max_workers=4) as ex:
        futs = [ex.submit(cb.generate, p, max_new_tokens=8, **kw)
                for p, kw in reqs]
        for i, f in enumerate(futs):
            np.testing.assert_array_equal(f.result(), solo[i],
                                          err_msg=f"request {i}")
    stats = cb.stats()
    assert stats["requests_served"] == 4
    assert stats["rows_in_segments"] > stats["segments_run"], stats


def test_chunked_joiner_prefill_matches_solo():
    """A long-prompt joiner on a prefill_chunk server prefills through
    chunks (request-thread dispatches) with solo-exact output, alone
    and next to short traffic."""
    from lambdipy_tpu.models import registry

    adapter = registry.get("llama-tiny").build()
    params = adapter.init_params(seed=0)
    server = adapter.make_server(params, prefill_chunk=16)
    cb = ContinuousBatcher(server, slots=2, segment=4,
                           group_prefill_max=8)
    long_prompt = list(range(1, 60))
    ref = server.generate(long_prompt, max_new_tokens=8)
    np.testing.assert_array_equal(
        cb.generate(long_prompt, max_new_tokens=8), ref)
    with ThreadPoolExecutor(max_workers=2) as ex:
        fa = ex.submit(cb.generate, long_prompt, max_new_tokens=8)
        fb = ex.submit(cb.generate, [5, 6, 7], max_new_tokens=8)
        np.testing.assert_array_equal(fa.result(), ref)
        np.testing.assert_array_equal(
            fb.result(), server.generate([5, 6, 7], max_new_tokens=8))


@pytest.mark.slow  # deliberate per-chunk sleeps (~17 s); chunked-joiner
# parity coverage stays fast via test_chunked_joiner_prefill_matches_solo
def test_decode_segments_proceed_while_joiner_prefills():
    """The interleave claim (VERDICT r5 #4): while a long joiner walks
    its prefill CHUNKS, the engine keeps running decode segments for
    in-flight rows — an already-active short request finishes before
    the slowed-down chunked prefill completes."""
    from lambdipy_tpu.models import registry
    from lambdipy_tpu.models.llama import LlamaServer

    adapter = registry.get("llama-tiny").build()
    params = adapter.init_params(seed=0)
    server = adapter.make_server(params, prefill_chunk=16)
    cb = ContinuousBatcher(server, slots=2, segment=4,
                           group_prefill_max=8)
    long_prompt = list(range(1, 100))  # 6 chunks of 16 + tail
    # warm every program first so the slow-chunk run times no compiles
    ref_long = server.generate(long_prompt, max_new_tokens=8)
    np.testing.assert_array_equal(
        cb.generate(long_prompt, max_new_tokens=8), ref_long)
    short_ref = server.generate([5, 6, 7], max_new_tokens=16)

    real_ext = LlamaServer._prefix_ext_fn

    def slow_ext(self, sbs):
        fn = real_ext(self, sbs)

        def wrapped(*a, **kw):
            time.sleep(0.25)  # make each chunk visibly slow
            return fn(*a, **kw)

        return wrapped

    done_at = {}
    with ThreadPoolExecutor(max_workers=2) as ex:
        orig = LlamaServer._prefix_ext_fn
        LlamaServer._prefix_ext_fn = slow_ext
        try:
            f_long = ex.submit(cb.generate, long_prompt,
                               max_new_tokens=8)
            time.sleep(0.05)  # the long joiner enters its chunk walk

            def short():
                out = cb.generate([5, 6, 7], max_new_tokens=16)
                done_at["short"] = time.monotonic()
                return out

            f_short = ex.submit(short)
            out_short = f_short.result()
            out_long = f_long.result()
            done_at["long"] = time.monotonic()
        finally:
            LlamaServer._prefix_ext_fn = orig
    np.testing.assert_array_equal(out_short, short_ref)
    np.testing.assert_array_equal(out_long, ref_long)
    # the short request finished while the long one was still chunking
    assert done_at["short"] < done_at["long"], done_at


def test_chunked_joiner_on_capped_engine():
    """A cache-capped engine (cache_len < max_len) chunk-prefills long
    joiners through its own continuation program key — solo parity
    holds and the program is AOT-able under the 3-tuple key."""
    from lambdipy_tpu.models import registry
    from lambdipy_tpu.models.llama import LlamaServer

    adapter = registry.get("llama-tiny").build()
    params = adapter.init_params(seed=0)
    server = adapter.make_server(params, prefill_chunk=16)
    cb = ContinuousBatcher(server, slots=2, segment=4, cache_len=64,
                           group_prefill_max=8)
    prompt = list(range(1, 41))  # 40 + 8 <= 64; 16 | 64
    ref = server.generate(prompt, max_new_tokens=8)
    np.testing.assert_array_equal(cb.generate(prompt, max_new_tokens=8),
                                  ref)
    key = next(k for k in server.buckets
               if k[0] == "stream_prefix" and len(k) == 3)
    assert key[2] == 64
    assert LlamaServer._aot_name(key) is not None
    assert server._aot_examples(key) is not None  # 3-tuple synthesizes


def test_engine_over_tp_sharded_server(cpu_devices):
    """The continuous engine over a TENSOR-PARALLEL server (the 8B
    recipe's default shape: batch_mode=continuous + tp mesh): packed
    decode matches the unsharded solo output."""
    from lambdipy_tpu.models import registry
    from lambdipy_tpu.parallel.mesh import make_mesh, use_mesh
    from lambdipy_tpu.parallel.sharding import shard_params

    adapter = registry.get("llama-tiny").build()
    params = adapter.init_params(seed=0)
    ref_server = adapter.make_server(params)
    refs = [ref_server.generate(p, max_new_tokens=8)
            for p in ([1, 2, 3], [9, 8, 7, 6])]

    mesh = make_mesh({"tp": 2}, devices=cpu_devices[:2])
    with use_mesh(mesh):
        sharded = shard_params(params, mesh, adapter.tp_rules)
    server = adapter.make_server(sharded, mesh=mesh)
    cb = ContinuousBatcher(server, slots=2, segment=4)
    with ThreadPoolExecutor(max_workers=2) as ex:
        fa = ex.submit(cb.generate, [1, 2, 3], max_new_tokens=8)
        fb = ex.submit(cb.generate, [9, 8, 7, 6], max_new_tokens=8)
        np.testing.assert_array_equal(fa.result(), refs[0])
        np.testing.assert_array_equal(fb.result(), refs[1])
    stats = cb.stats()
    assert stats["rows_in_segments"] > stats["segments_run"], stats


def test_engine_over_sp_mesh_long_context_path(cpu_devices, count_sp_decode):
    """Continuous batching over the LONG-CONTEXT serving shape
    (attn_backend='ring' + sp mesh): engine-packed rows decode through
    sequence-sharded sp_decode steps (asserted to trace — code-review
    r5 caught the vacuous dense-vs-dense version) and match the dense
    unsharded solo outputs."""
    from lambdipy_tpu.models import registry
    from lambdipy_tpu.parallel.mesh import make_mesh, use_mesh
    from lambdipy_tpu.parallel.sharding import shard_params

    calls = count_sp_decode

    adapter = registry.get("llama-tiny").build()
    params = adapter.init_params(seed=0)
    dense = adapter.make_server(params)
    refs = [dense.generate(p, max_new_tokens=8)
            for p in ([1, 2, 3], [9, 8, 7, 6])]

    ring = registry.get("llama-tiny").build(
        extra={"attn_backend": "ring"})
    assert ring.config.attn_backend == "ring"
    mesh = make_mesh({"sp": 2}, devices=cpu_devices[:2])
    with use_mesh(mesh):
        sp_params = shard_params(params, mesh, ring.tp_rules)
    server = ring.make_server(sp_params, mesh=mesh)
    cb = ContinuousBatcher(server, slots=2, segment=4)
    with ThreadPoolExecutor(max_workers=2) as ex:
        fa = ex.submit(cb.generate, [1, 2, 3], max_new_tokens=8)
        fb = ex.submit(cb.generate, [9, 8, 7, 6], max_new_tokens=8)
        np.testing.assert_array_equal(fa.result(), refs[0])
        np.testing.assert_array_equal(fb.result(), refs[1])
    assert calls["n"] > 0, "sp decode path never traced"
    stats = cb.stats()
    assert stats["rows_in_segments"] > stats["segments_run"], stats


def test_warm_group_prefill_precompiles_burst_programs(tiny_server):
    """warm_group_prefill compiles every power-of-two group-prefill
    program up to slots, so a later joiner burst compiles NOTHING — on
    a remote-compile transport the unwarmed first burst paid ~30 s of
    compiles inside request latency (round-5 concurrent measurement)."""
    cb = ContinuousBatcher(tiny_server, slots=4, segment=4)
    assert cb.warm_group_prefill() == 3  # bb = 2, 4 + the long bucket
    before = tiny_server.compile_count
    for k in (2, 3, 4):  # 3 rides the bb=4 bucket
        entries = [dict(row=[5, 6], s=2, temperature=None, top_k=None,
                        top_p=None, seed=None) for _ in range(k)]
        cb._prefill_group(entries)
    assert tiny_server.compile_count == before, \
        "burst group-prefill must reuse the warmed programs"


@pytest.mark.slow  # one extra 4x64 prefill compile; the warm COUNTS
# (which include the long bucket) are asserted non-slow above/below
def test_warm_group_prefill_covers_long_prompt_bucket(tiny_server):
    """Prompts above the min bucket used to stay a residual compile
    cliff (ADVICE r5 continuous.py:222): the warm now also compiles the
    longest group-prefillable prompt bucket at the full-burst joiner
    count, so a burst of long-ish prompts compiles nothing. Prompt
    buckets BETWEEN the two warmed families still compile at first use
    — that residual is documented in warm_group_prefill's docstring."""
    cb = ContinuousBatcher(tiny_server, slots=4, segment=4)
    cb.warm_group_prefill()
    before = tiny_server.compile_count
    s_warm = min(cb.group_prefill_max, cb.cache_len // 2)
    entries = [dict(row=list(range(1, s_warm + 1)), s=s_warm,
                    temperature=None, top_k=None, top_p=None, seed=None)
               for _ in range(4)]
    cb._prefill_group(entries)
    assert tiny_server.compile_count == before, \
        "a full burst at the long-prompt bucket must hit warm programs"


def test_handler_daemon_warms_group_prefill(tmp_path):
    """The background warm daemon reaches the engine's group-prefill
    programs after the first invoke and reports progress in stats —
    the wiring the warm_group_prefill flag controls."""
    from tests.test_runtime import make_model_bundle
    from lambdipy_tpu.runtime.loader import load_bundle

    bundle = make_model_bundle(
        tmp_path, model="llama-tiny",
        handler="lambdipy_tpu.runtime.handlers:generate_handler",
        # explicit: the test helper defaults the warm daemon OFF for
        # suite economy; this test IS the daemon wiring
        extra={"max_new_tokens": "4", "batch_mode": "continuous",
               "batch_max": "4", "warm_group_prefill": "1"})
    r = load_bundle(bundle, warmup=True)
    assert r.warmup_result["ok"]
    deadline = time.monotonic() + 60
    done: list = []
    while time.monotonic() < deadline:
        done = r.state.stats().get("warm_buckets", {}).get("done", [])
        if any(str(d).startswith("group_prefill:") for d in done):
            break
        time.sleep(0.5)
    assert any(str(d).startswith("group_prefill:") for d in done), \
        r.state.stats()


def test_warm_group_prefill_covers_non_pow2_slots(tiny_server):
    """A full burst on a 6-slot engine buckets UP to the 8-row program
    (_next_bucket(6) = 8): warm must compile that bucket too, or the
    largest burst pays the compile cliff the warm exists to remove."""
    cb = ContinuousBatcher(tiny_server, slots=6, segment=4)
    assert cb.warm_group_prefill() == 4  # buckets 2, 4, 8 + long bucket
    before = tiny_server.compile_count
    entries = [dict(row=[5, 6], s=2, temperature=None, top_k=None,
                    top_p=None, seed=None) for _ in range(6)]
    cb._prefill_group(entries)
    assert tiny_server.compile_count == before
