"""Local artifact registry: the offline analogue of the reference's
GitHub-Releases prebuilt-artifact index + download cache (SURVEY.md §3.1
#4/#9).

Layout (content-addressed, one dir per artifact id):

    <root>/
      artifacts/<artifact_id>/bundle/...     # the built bundle tree
      artifacts/<artifact_id>/manifest.json  # provenance + per-file hashes
      index.json                             # artifact_id -> summary

``publish`` moves a built bundle in; ``fetch`` returns a cached path (the
"hit: download artifact; cache" branch of SURVEY.md §4 A). A remote registry
(GCS bucket) would implement the same interface; only the local one is
constructible in this no-network environment.
"""

from __future__ import annotations

import json
import shutil
import time
from dataclasses import dataclass
from pathlib import Path

from lambdipy_tpu.utils.fsutil import atomic_write_text, copy_tree, dir_size

DEFAULT_ROOT = Path.home() / ".lambdipy-tpu" / "registry"


class RegistryError(RuntimeError):
    pass


@dataclass(frozen=True)
class ArtifactInfo:
    artifact_id: str
    recipe: str
    version: str
    device: str
    size_bytes: int
    created: float


class ArtifactRegistry:
    def __init__(self, root: Path | None = None):
        self.root = Path(root) if root else DEFAULT_ROOT
        self.artifacts_dir = self.root / "artifacts"
        self.index_path = self.root / "index.json"
        self.artifacts_dir.mkdir(parents=True, exist_ok=True)

    def _load_index(self) -> dict:
        if self.index_path.exists():
            return json.loads(self.index_path.read_text())
        return {}

    def _save_index(self, index: dict) -> None:
        atomic_write_text(self.index_path, json.dumps(index, indent=1, sort_keys=True))

    def list(self) -> list[ArtifactInfo]:
        return [ArtifactInfo(**v) for v in self._load_index().values()]

    def has(self, artifact_id: str) -> bool:
        return (self.artifacts_dir / artifact_id / "bundle").is_dir()

    def fetch(self, artifact_id: str) -> Path:
        """Return the bundle tree for an artifact (the cache-hit path)."""
        path = self.artifacts_dir / artifact_id / "bundle"
        if not path.is_dir():
            raise RegistryError(f"artifact {artifact_id!r} not in registry")
        return path

    def publish(self, artifact_id: str, bundle_dir: Path, *, recipe: str,
                version: str, device: str, manifest: dict | None = None) -> Path:
        """Publish a built bundle into the registry (SURVEY.md §4 C, minus
        the GitHub upload — the registry dir is the release store)."""
        dst = self.artifacts_dir / artifact_id
        if dst.exists():
            shutil.rmtree(dst)
        dst.mkdir(parents=True)
        copy_tree(Path(bundle_dir), dst / "bundle")
        if manifest is not None:
            atomic_write_text(dst / "manifest.json", json.dumps(manifest, indent=1, sort_keys=True))
        index = self._load_index()
        index[artifact_id] = {
            "artifact_id": artifact_id,
            "recipe": recipe,
            "version": version,
            "device": device,
            "size_bytes": dir_size(dst / "bundle"),
            "created": time.time(),
        }
        self._save_index(index)
        return dst / "bundle"

    def delete(self, artifact_id: str) -> None:
        dst = self.artifacts_dir / artifact_id
        if dst.exists():
            shutil.rmtree(dst)
        index = self._load_index()
        index.pop(artifact_id, None)
        self._save_index(index)
