"""Deterministic fault injection for the serve path.

The continuous engine's recovery machinery (watchdog, replay-on-restart,
degradation ladder — runtime/continuous.py) only earns trust if every
path through it runs in CI, not just when a TPU transport happens to
wedge. This module gives tests and ``bench.py --chaos`` a deterministic
way to make named SITES misbehave:

========================  ====================================================
site                      where it fires
========================  ====================================================
``segment_dispatch``      the engine thread dispatching a decode segment
``segment_fetch``         the per-segment ``device_get`` in the collector
``group_prefill``         the engine's ragged b-row joiner prefill
``prefix_assemble``       continue-prefill from a cached prefix KV
``prefix_walk``           the prefix store's cold-walk, once per chunk
                          dispatch (an exception fails the walk open —
                          the request serves unrouted; a delay models
                          per-chunk prefill device time)
``transport``             the ``block_until_ready`` device wait before fetch
``page_alloc``            the paged-KV pool taking pages for an admission
``route_connect``         the fleet router opening a replica connection
``route_body``            the router reading a replica response body
``route_latency``         the router's forward path (network latency site)
``probe``                 the replica pool's per-replica health probe
``kv_ship``               the router's prefill→decode KV-block ship (fires
                          once per ship attempt, before the export leg)
``kv_ship_chunk``         the router's pipelined ship relay, once per
                          relayed KV chunk frame (an exception is a
                          MID-STREAM transfer failure — the receiving
                          import aborts its staged pages and the request
                          degrades to mixed-mode; a delay is per-chunk
                          synthetic wire time, the PR-5/PR-12 RTT idiom
                          ``bench.py --disagg-rtt`` prices both ship
                          modes with)
``session_pin``           the prefix store pinning a session's radix head
                          (fires once per turn, before any pin mutation;
                          an exception fails the pin OPEN — the turn
                          serves unpinned, counted)
``session_failover``      the router re-homing a session off a dead/
                          drained replica (fires before the re-ship legs;
                          an exception skips the re-ship — the new home
                          re-prefills locally, counted)
========================  ====================================================

The ``route_*``/``probe`` sites live in the FLEET layer (fleet/router.py
and fleet/pool.py): they make the *network* lie — dropped connections
(``route_connect:exception``), connections dying mid-body
(``route_body:exception``), latency spikes
(``route_latency:delay@ms=300``), and flapping replicas
(``probe:exception@seg=3,n=6``) — so ``bench.py --chaos-fleet`` can run
a drop/latency/flap matrix against a live fleet with the same
deterministic call counting the engine sites get.

Each site can raise (``exception``), stall (``delay``, ``ms=``) or block
indefinitely (``hang`` — until the plan is released, the watchdog aborts
the wait, or a hard cap expires so test runs never leak threads).

Specs are strings so they travel through env/bundle extras::

    LAMBDIPY_FAULT="segment_fetch:hang@seg=3"      # hang from the 3rd fetch on
    LAMBDIPY_FAULT="group_prefill:exception"        # raise on the 1st call
    LAMBDIPY_FAULT="transport:delay@ms=200,n=2"     # 200 ms stall, twice
    LAMBDIPY_FAULT="segment_fetch:exception;transport:delay"  # multiple rules

Grammar: ``site:kind[@key=val,key=val]`` joined by ``;``. ``seg=N`` is
the 1-based per-site call index where the rule starts firing (default 1),
``n=K`` how many calls it fires for (default 1 for exception/delay,
unlimited for hang; ``n=inf`` forces unlimited), ``ms=X`` the delay
duration. Call counting is per site and strictly deterministic — the
whole point is that a chaos case replays identically run after run.

Sites live in a structured ``REGISTRY`` (:class:`FaultSite`: owning
layer, arming env var, semantics note) that feeds the chaos soak's
nemesis menu (:func:`list_sites`) and the docs table; a grep-based test
asserts every ``check()`` call site in the tree is registered. Plans
also support RUNTIME arming (:meth:`FaultPlan.arm` /
:meth:`FaultPlan.clear` / :meth:`FaultPlan.armed` — the replica's
``POST /v1/debug/faults`` control surface and the ``faults.armed``
metrics block), so composed faults can start and stop on a nemesis
timeline without restarting the process.
"""

from __future__ import annotations

import math
import os
import threading
import time
from dataclasses import dataclass, field

@dataclass(frozen=True)
class FaultSite:
    """One registered injection point. ``owner`` names the layer whose
    plan drives it (engine | store | pool | router), ``env`` the spec
    env var that arms it in a live process (engine/store sites ride the
    replica's ``LAMBDIPY_FAULT``; pool/router sites the fleet process's
    ``LAMBDIPY_FLEET_FAULT``), ``note`` a one-line semantics summary.
    The chaos soak's nemesis menu and the docs table are both derived
    from this registry — and a grep-based test asserts every
    ``faults.check(...)``/``_device_wait(...)`` call site in the tree is
    registered here, so a new site cannot silently dodge the soak."""

    name: str
    owner: str
    env: str
    note: str


_ENGINE_ENV = "LAMBDIPY_FAULT"
_FLEET_ENV = "LAMBDIPY_FLEET_FAULT"

REGISTRY: dict[str, FaultSite] = {s.name: s for s in (
    FaultSite("segment_dispatch", "engine", _ENGINE_ENV,
              "the engine thread dispatching a decode segment"),
    FaultSite("segment_fetch", "engine", _ENGINE_ENV,
              "the per-segment device_get in the collector"),
    FaultSite("group_prefill", "engine", _ENGINE_ENV,
              "the engine's ragged b-row joiner prefill"),
    FaultSite("prefix_assemble", "engine", _ENGINE_ENV,
              "continue-prefill from a cached prefix KV"),
    FaultSite("prefix_walk", "store", _ENGINE_ENV,
              "the prefix store's cold walk, once per chunk dispatch "
              "(exception fails the walk open; delay models per-chunk "
              "prefill device time)"),
    FaultSite("transport", "engine", _ENGINE_ENV,
              "the block_until_ready device wait before fetch"),
    FaultSite("page_alloc", "store", _ENGINE_ENV,
              "the paged-KV pool taking pages for an admission"),
    FaultSite("session_pin", "store", _ENGINE_ENV,
              "the prefix store pinning a session's radix head (fails "
              "OPEN: the turn serves unpinned, counted)"),
    FaultSite("offload_stall", "store", _ENGINE_ENV,
              "the host offload arena's batched page re-online (delay "
              "= a slow fetch, timed as a re-online stall; exception "
              "= a FAILED re-online — the caller recomputes the page "
              "via prefill, counted, never a wrong token)"),
    # fleet-layer (router/pool) network sites
    FaultSite("route_connect", "router", _FLEET_ENV,
              "the fleet router opening a replica connection"),
    FaultSite("route_body", "router", _FLEET_ENV,
              "the router reading a replica response body"),
    FaultSite("route_latency", "router", _FLEET_ENV,
              "the router's forward path (network latency site)"),
    FaultSite("probe", "pool", _FLEET_ENV,
              "the replica pool's per-replica health probe"),
    FaultSite("kv_ship", "router", _FLEET_ENV,
              "the router's prefill->decode KV ship, once per attempt"),
    FaultSite("kv_ship_chunk", "router", _FLEET_ENV,
              "the pipelined ship relay, once per relayed KV chunk "
              "frame (exception = mid-stream transfer failure; delay = "
              "per-chunk synthetic wire time)"),
    FaultSite("session_failover", "router", _FLEET_ENV,
              "the router re-homing a session off a dead/drained "
              "replica (exception skips the re-ship, counted)"),
)}

# tuple view kept for spec validation, matrix iteration (bench.py
# --chaos walks it) and backward compatibility with pre-registry callers
SITES = tuple(REGISTRY)
KINDS = ("exception", "delay", "hang")


def list_sites(*, owner: str | None = None,
               env: str | None = None) -> list[FaultSite]:
    """Registry query feeding the nemesis menu and the docs table:
    all sites, optionally filtered by owning layer or arming env var."""
    return [s for s in REGISTRY.values()
            if (owner is None or s.owner == owner)
            and (env is None or s.env == env)]
_KIND_ALIASES = {"error": "exception", "raise": "exception",
                 "sleep": "delay", "stall": "delay", "block": "hang"}

# injected hangs still resolve after this many seconds even if nothing
# releases or aborts them — a safety net so a test that forgets teardown
# cannot leak a thread for the life of the process
HANG_CAP_S = 300.0


class InjectedFault(RuntimeError):
    """An exception (or aborted hang) raised by the fault layer.

    ``fault_site`` lets the engine's failure handler attribute the
    failure without string-parsing the message."""

    def __init__(self, site: str, kind: str, occurrence: int):
        self.fault_site = site
        self.fault_kind = kind
        self.occurrence = occurrence
        super().__init__(
            f"injected {kind} at {site} (call #{occurrence})")


class EngineWatchdogTimeout(TimeoutError):
    """A device-side wait exceeded the engine watchdog. Raised to the
    waiters of an engine the watchdog declared wedged, and by guarded
    request-thread waits whose injected hang the watchdog aborted."""

    def __init__(self, site: str, timeout_s: float):
        self.fault_site = f"watchdog:{site}"
        super().__init__(
            f"engine watchdog: {site} wait exceeded {timeout_s:.3g}s")


@dataclass
class FaultRule:
    site: str
    kind: str
    seg: int = 1            # 1-based call index where firing starts
    n: float = 1            # firings (math.inf = permanent)
    ms: float = 50.0        # delay duration
    fired: int = 0

    def matches(self, count: int) -> bool:
        return self.seg <= count and self.fired < self.n

    def describe(self) -> str:
        span = "inf" if math.isinf(self.n) else str(int(self.n))
        return (f"{self.site}:{self.kind}@seg={self.seg},n={span}"
                + (f",ms={self.ms:g}" if self.kind == "delay" else ""))


def parse_spec(spec: str | None) -> list[FaultRule]:
    """Parse a fault spec string into rules (shared by
    :meth:`FaultPlan.from_spec` and the runtime :meth:`FaultPlan.arm`)."""
    rules: list[FaultRule] = []
    for part in (spec or "").split(";"):
        part = part.strip()
        if not part:
            continue
        head, _, params = part.partition("@")
        site, sep, kind = head.partition(":")
        site, kind = site.strip(), kind.strip().lower()
        kind = _KIND_ALIASES.get(kind, kind)
        if not sep or site not in SITES or kind not in KINDS:
            raise ValueError(
                f"bad fault spec {part!r}: want site:kind with site in "
                f"{SITES} and kind in {KINDS}")
        rule = FaultRule(site=site, kind=kind,
                         n=(math.inf if kind == "hang" else 1))
        for kv in filter(None, (p.strip() for p in params.split(","))):
            key, eq, val = kv.partition("=")
            key = key.strip().lower()
            try:
                if key in ("seg", "at"):
                    rule.seg = max(1, int(val))
                elif key == "n":
                    rule.n = math.inf if val.strip() in ("inf", "-1") \
                        else max(1, int(val))
                elif key == "ms":
                    rule.ms = max(0.0, float(val))
                else:
                    raise ValueError(key)
            except ValueError:
                raise ValueError(
                    f"bad fault param {kv!r} in {part!r} "
                    f"(known: seg=N, n=K|inf, ms=X)") from None
        rules.append(rule)
    return rules


class FaultPlan:
    """A deterministic set of :class:`FaultRule`\\ s plus the per-site
    call counters they key on. An empty plan is a no-op and costs one
    ``if`` per site check — safe to leave wired in production.

    Rules may also be armed and cleared AT RUNTIME (:meth:`arm` /
    :meth:`clear`) — the chaos soak's nemesis drives a live replica's
    plan over ``POST /v1/debug/faults`` this way, and a cleared plan
    releases its in-flight hangs without poisoning later ones."""

    def __init__(self, rules: list[FaultRule] | None = None):
        self.rules = list(rules or ())
        self._counts: dict[str, int] = {}
        self._lock = threading.Lock()
        self._release = threading.Event()

    # -- construction --------------------------------------------------------

    @classmethod
    def empty(cls) -> "FaultPlan":
        return cls([])

    @classmethod
    def from_spec(cls, spec: str | None) -> "FaultPlan":
        """Parse ``site:kind@k=v,...;site2:...``; unknown sites/kinds and
        malformed params raise ``ValueError`` — a typo in a chaos spec
        must fail the run loudly, not silently test nothing."""
        return cls(parse_spec(spec))

    @classmethod
    def from_env(cls, environ=None, *, var: str = "LAMBDIPY_FAULT"
                 ) -> "FaultPlan":
        """``var`` selects the env knob: the engine reads
        ``LAMBDIPY_FAULT``; the fleet layer reads
        ``LAMBDIPY_FLEET_FAULT`` so arming a replica's engine sites
        never silently arms the router in the same shell."""
        return cls.from_spec((environ or os.environ).get(var))

    # -- the injection point -------------------------------------------------

    def check(self, site: str, interrupt: threading.Event | None = None
              ) -> None:
        """Called once per site invocation. No-op without a matching
        rule; otherwise sleeps (delay), raises (exception), or blocks
        (hang) until :meth:`release`, the ``interrupt`` event (the
        watchdog's abort), or the hard cap — then raises, because a wait
        the system gave up on must not look like a success."""
        if not self.rules:
            return
        with self._lock:
            count = self._counts.get(site, 0) + 1
            self._counts[site] = count
            rule = next((r for r in self.rules
                         if r.site == site and r.matches(count)), None)
            if rule is not None:
                rule.fired += 1
        if rule is None:
            return
        if rule.kind == "delay":
            time.sleep(rule.ms / 1e3)
            return
        if rule.kind == "hang":
            # capture the CURRENT release event: clear() sets it and then
            # installs a fresh one, so this hang resolves while a
            # later-armed hang still blocks (runtime re-arming must not
            # inherit a permanently-released plan)
            release = self._release
            deadline = time.monotonic() + HANG_CAP_S
            while time.monotonic() < deadline:
                if release.wait(0.02):
                    break
                if interrupt is not None and interrupt.is_set():
                    break
        raise InjectedFault(site, rule.kind, count)

    # -- runtime arming (nemesis control surface) ----------------------------

    def arm(self, spec: str) -> list[str]:
        """Parse ``spec`` and ADD its rules to the live plan (call
        counters keep running — a rule armed mid-soak fires on the next
        matching call). Returns the added rules' descriptions; raises
        ``ValueError`` on a bad spec, touching nothing."""
        rules = parse_spec(spec)
        with self._lock:
            self.rules.extend(rules)
        return [r.describe() for r in rules]

    def clear(self) -> int:
        """Drop every rule and release in-flight hangs, leaving the plan
        re-armable: waiters blocked on the old release event resolve
        (raising ``InjectedFault``, as an abandoned wait must), while
        hangs armed LATER block on the fresh event. Call counters are
        kept — they are the deterministic spine replay depends on.
        Returns the number of rules cleared."""
        with self._lock:
            n = len(self.rules)
            self.rules = []
            released, self._release = self._release, threading.Event()
        released.set()
        return n

    def armed(self) -> dict:
        """Live-plan snapshot for ``/metrics`` (``faults.armed``): the
        armed sites/kinds with remaining fire counts, plus the per-site
        call counters — so a soak run (or a stray ``LAMBDIPY_FAULT``
        left set in prod) is visible at the front door."""
        with self._lock:
            rules = [{
                "site": r.site,
                "kind": r.kind,
                "seg": r.seg,
                "n": ("inf" if math.isinf(r.n) else int(r.n)),
                **({"ms": r.ms} if r.kind == "delay" else {}),
                "fired": r.fired,
                "remaining": ("inf" if math.isinf(r.n)
                              else max(0, int(r.n) - r.fired)),
            } for r in self.rules]
            counts = dict(self._counts)
        return {"active": bool(rules),
                "sites": sorted({r["site"] for r in rules}),
                "rules": rules,
                "counts": counts}

    # -- lifecycle / introspection -------------------------------------------

    def release(self) -> None:
        """Unblock every in-flight (and future) hang — test teardown."""
        self._release.set()

    def active(self) -> bool:
        return bool(self.rules)

    def counts(self) -> dict:
        with self._lock:
            return dict(self._counts)

    def describe(self) -> list[str]:
        return [r.describe() for r in self.rules]
